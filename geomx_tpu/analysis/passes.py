"""The Graft Auditor's pass catalog (docs/analysis.md).

Rule ids:

- ``GX-COLLECTIVE-001``  cross-program collective-signature divergence
  (would deadlock or silently diverge a multi-party mesh at run time)
- ``GX-COLLECTIVE-002``  a membership/pipeline recompile changed the
  collective program (Trainer.apply_membership boundary)
- ``GX-DONATE-001``      donated buffer has no aliased output (the
  program still reads it after every aliasing opportunity — the
  donation is a lie and the caller's buffer dies for nothing)
- ``GX-DONATE-002``      an expected state buffer (EF residual,
  pipeline double-buffer) is not covered by input_output_aliases
- ``GX-DTYPE-001``       fp32 compute op on a declared-16-bit path
- ``GX-DTYPE-002``       wire-dtype accounting mismatch: the bytes the
  traced collectives actually move disagree with
  ``Compressor.wire_bytes``
- ``GX-PURITY-001``      a dense(-sized) payload crosses the wire on a
  compressed dc path (the decompress-before-collective regression
  PR 4's hand-rolled HLO check guarded against, generalized)
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from geomx_tpu.analysis.core import (AuditContext, AuditPass, EqnSite,
                                     Finding, aval_bytes, aval_sig,
                                     walk_jaxpr)

# every cross-device primitive jax can put in a shard_map'd program on
# this jaxlib; psum2/all_gather_invariant are newer spellings kept for
# forward-compat (bench's DCE counter uses the same set)
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "all_gather", "all_gather_invariant", "all_to_all",
    "ppermute", "pbroadcast", "psum_scatter", "reduce_scatter"})

# jaxpr-level ops that materialize a full-size intermediate when they
# appear dense-shaped (the XLA scatter/cumsum expansions the fused
# kernels exist to remove)
DENSE_MATERIALIZING_PRIMS = frozenset({
    "scatter", "scatter-add", "scatter-mul", "scatter-min", "scatter-max",
    "cumsum", "cumlogsumexp", "cummax", "cummin", "cumprod", "sort"})

# the heavy compute ops the dtype-flow leak rule inspects: an fp32
# matmul/conv on a declared-bf16 path burns 2x the MXU bandwidth the
# declaration promised
_HEAVY_COMPUTE_PRIMS = frozenset({"dot_general", "conv_general_dilated"})


def _collective_axes(eqn) -> Tuple[str, ...]:
    """The named mesh axes an equation communicates over (psum spells
    them ``axes``, the gather/permute family ``axis_name``)."""
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(str(a) for a in axes)


# ---------------------------------------------------------------------------
# collective-consistency
# ---------------------------------------------------------------------------

def count_collectives(jaxpr, axis: Optional[str] = None) -> int:
    """Number of collective equations in a traced program (recursing
    through pjit/shard_map/scan/cond bodies), optionally restricted to
    those communicating over the named ``axis`` — the counter bench's
    --compare-bucketing/--compare-pipeline accounting is built on."""
    n = 0
    for site in walk_jaxpr(jaxpr):
        if site.primitive in COLLECTIVE_PRIMS:
            if axis is None or axis in _collective_axes(site.eqn):
                n += 1
    return n


def collective_signature(jaxpr) -> Tuple[Tuple[str, Tuple[str, ...],
                                               Tuple[Tuple[int, ...], str],
                                               tuple], ...]:
    """The ordered named-axis collective signature of a traced program:
    one ``(op, axes, (shape, dtype), extras)`` entry per collective
    *operand*, in deterministic walk order.  Two SPMD programs whose
    signatures differ cannot safely share a mesh — the first differing
    entry deadlocks (count/op mismatch) or silently mis-aggregates
    (shape/dtype/routing mismatch).

    A multi-operand collective (``lax.pmean`` over a dict traces ONE
    psum equation carrying every leaf) is normalized to one entry per
    operand: the wire payload sequence is the invariant, not the fusion
    packaging — XLA's all-reduce combiner merges/splits adjacent
    same-axis collectives regardless of how the jaxpr grouped them, so
    ``psum(a, b)`` and ``psum(a); psum(b)`` describe the same program.
    ``extras`` carries routing parameters that change peer pairing
    (ppermute's ``perm``, any ``axis_index_groups``)."""
    sig = []
    for site in walk_jaxpr(jaxpr):
        if site.primitive not in COLLECTIVE_PRIMS:
            continue
        extras = []
        perm = site.eqn.params.get("perm")
        if perm is not None:
            extras.append(("perm", tuple(map(tuple, perm))))
        groups = site.eqn.params.get("axis_index_groups")
        if groups is not None:
            extras.append(("axis_index_groups",
                           tuple(tuple(g) for g in groups)))
        axes = _collective_axes(site.eqn)
        for v in site.eqn.invars:
            if hasattr(v, "aval"):
                sig.append((site.primitive, axes, aval_sig(v.aval),
                            tuple(extras)))
    return tuple(sig)


def diff_collective_signatures(
        sigs: Mapping[str, tuple],
        rule_id: str = "GX-COLLECTIVE-001") -> List[Finding]:
    """Diff named collective signatures pairwise against the first
    entry; one finding per divergent party naming the first differing
    position (op/axes/operands or a missing/extra collective)."""
    findings: List[Finding] = []
    items = list(sigs.items())
    if len(items) < 2:
        return findings
    ref_name, ref = items[0]
    for name, sig in items[1:]:
        if sig == ref:
            continue
        pos = next((i for i, (a, b) in enumerate(zip(ref, sig)) if a != b),
                   min(len(ref), len(sig)))
        a = ref[pos] if pos < len(ref) else None
        b = sig[pos] if pos < len(sig) else None
        findings.append(Finding(
            rule_id=rule_id, severity="error",
            message=(f"collective sequence diverges between {ref_name!r} "
                     f"({len(ref)} collectives) and {name!r} ({len(sig)}) "
                     f"at position {pos}: {a} vs {b} — this program pair "
                     "deadlocks or silently diverges on a shared mesh"),
            detail={"parties": [ref_name, name], "position": pos,
                    "reference": a, "divergent": b}))
    return findings


def audit_cross_party(configs: Mapping[str, Any],
                      build: Optional[Callable[[Any], Any]] = None,
                      rule_id: str = "GX-COLLECTIVE-001") -> List[Finding]:
    """Diff the collective signature of a step program across party
    configurations — the trace-time form of "would this deployment
    deadlock at 2x2 mesh scale".

    ``configs`` maps a party label to any of: a (closed) jaxpr, a
    zero-arg callable returning one, or — with ``build`` given — an
    opaque config object ``build`` turns into a jaxpr.  Signatures are
    extracted per party and diffed against the first entry.  Empty
    result = every party traces the same collective program.
    """
    sigs: Dict[str, tuple] = {}
    for name, cfg in configs.items():
        if build is not None:
            jx = build(cfg)
        elif callable(cfg) and not hasattr(cfg, "eqns") \
                and not hasattr(cfg, "jaxpr"):
            jx = cfg()
        else:
            jx = cfg
        sigs[name] = (jx if isinstance(jx, tuple)
                      else collective_signature(jx))
    return diff_collective_signatures(sigs, rule_id=rule_id)


class CollectiveConsistencyPass(AuditPass):
    """Single-program form: record the signature into ``ctx.extras``
    (for cross-program diffing by the caller) and flag constructs that
    make per-party program shape diverge by design —
    ``axis_index_groups`` partitions a named axis into subgroups, so two
    parties' traces only match if every party computed the same groups."""

    rule_id = "GX-COLLECTIVE-001"

    def run(self, jaxpr, ctx: AuditContext) -> List[Finding]:
        findings: List[Finding] = []
        ctx.extras["collective_signature"] = collective_signature(jaxpr)
        for site in walk_jaxpr(jaxpr):
            if site.primitive not in COLLECTIVE_PRIMS:
                continue
            if site.eqn.params.get("axis_index_groups") is not None:
                findings.append(self.finding(
                    f"{site.primitive} uses axis_index_groups: subgroup "
                    "membership is baked per trace and diverges across "
                    "parties unless every party derives identical groups",
                    site=site, severity="warning"))
        return findings


# ---------------------------------------------------------------------------
# donation / aliasing
# ---------------------------------------------------------------------------

# StableHLO argument attributes jax emits for donation.  Unsharded jit:
# an aliased donor carries tf.aliasing_output = <result index>; a donor
# the program still needs (read after every aliasing opportunity) is
# left attribute-free and jax warns "Some donated buffers were not
# usable".  Sharded (shard_map/NamedSharding) programs defer the
# decision to the compiler and mark every donor jax.buffer_donor=true —
# the verdict then lives in the compiled module's input_output_alias
# table (:func:`parse_compiled_aliases`).
_ALIAS_ATTR = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")
_DONOR_ATTR = re.compile(r"jax\.buffer_donor\s*=\s*true")
_TENSOR_TY = re.compile(r"tensor<([0-9x]*)x?([a-z][a-z0-9]+)>")
_COMPILED_ALIAS = re.compile(r"\{[0-9, ]*\}:\s*\((\d+)")

# MLIR element types -> numpy dtype names (the subset this codebase
# puts on program boundaries)
_MLIR_DTYPES = {"f64": "float64", "f32": "float32", "f16": "float16",
                "bf16": "bfloat16", "i64": "int64", "i32": "int32",
                "i16": "int16", "i8": "int8", "ui8": "uint8",
                "ui32": "uint32", "i1": "bool"}


def _main_args(lowered_text: str) -> List[dict]:
    """Parse the entry computation's argument list out of StableHLO
    text: per-arg tensor type plus donation/aliasing attributes."""
    m = re.search(r"func\.func\s+(?:public\s+)?@main\s*\((.*?)\)\s*->",
                  lowered_text, re.S)
    if not m:
        return []
    args: List[dict] = []
    # split on "%argN:" boundaries — attribute dicts contain commas, so a
    # naive comma split would shred them
    for piece in re.split(r"%arg\d+\s*:", m.group(1))[1:]:
        ty = _TENSOR_TY.search(piece)
        dims, dtype = (ty.group(1), ty.group(2)) if ty else ("", "?")
        shape = tuple(int(d) for d in dims.split("x") if d) if dims else ()
        size = 1
        for d in shape:
            size *= d
        alias = _ALIAS_ATTR.search(piece)
        args.append({
            "shape": shape, "dtype": _MLIR_DTYPES.get(dtype, dtype),
            "size": size,
            "aliased_output": int(alias.group(1)) if alias else None,
            "donor_deferred": bool(_DONOR_ATTR.search(piece)),
        })
    return args


def parse_compiled_aliases(compiled_text: str) -> frozenset:
    """Parameter indices the compiled module's ``input_output_alias``
    table aliases into outputs (``jax.stages.Compiled.as_text()``) —
    the ground truth for sharded programs whose StableHLO only says
    ``jax.buffer_donor``."""
    start = compiled_text.find("input_output_alias={")
    if start < 0:
        return frozenset()
    i = compiled_text.index("{", start)
    depth = 0
    for j in range(i, len(compiled_text)):
        if compiled_text[j] == "{":
            depth += 1
        elif compiled_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
    else:
        return frozenset()
    body = compiled_text[i + 1:j]
    return frozenset(int(p) for p in _COMPILED_ALIAS.findall(body))


class DonationPass(AuditPass):
    """Donation honesty on a lowered program (``ctx.lowered_text``):

    - GX-DONATE-001: a donated argument with no aliased output — the
      program reads the buffer after every chance to reuse it, so the
      caller loses the buffer AND the memory saving.  Donated flat-arg
      positions come from ``ctx.extras["donated_positions"]`` (this
      jaxlib leaves unusable donors attribute-free in unsharded module
      text, so intent must ride in from the caller) plus any arg the
      text itself marks.  A ``jax.buffer_donor`` arg defers the verdict
      to the compiler: it is judged against
      ``ctx.extras["compiled_alias_params"]``
      (:func:`parse_compiled_aliases`) when given, and left unjudged
      otherwise;
    - GX-DONATE-002: an expected-aliased buffer signature
      (``ctx.extras["expect_aliased"]``, e.g. the EF-residual and
      pipeline double-buffer leaves) has no aliased argument of that
      shape/dtype — the state round-trip reallocates every step.
    """

    rule_id = "GX-DONATE-001"

    def run(self, jaxpr, ctx: AuditContext) -> List[Finding]:
        text = ctx.lowered_text
        if not text:
            return []
        args = _main_args(text)
        donated = set(ctx.extras.get("donated_positions", ()))
        donated.update(i for i, a in enumerate(args)
                       if a["donor_deferred"]
                       or a["aliased_output"] is not None)
        compiled = ctx.extras.get("compiled_alias_params")
        findings: List[Finding] = []

        def _is_aliased(i, a):
            if a["aliased_output"] is not None:
                return True
            if compiled is not None:
                return i in compiled
            # deferred donor with no compiled table: unjudgeable — only
            # a donation the LOWERING already dropped is a finding
            return a["donor_deferred"]

        for i, a in enumerate(args):
            if i in donated and not _is_aliased(i, a):
                findings.append(self.finding(
                    f"donated arg {i} ({a['shape']} {a['dtype']}) has no "
                    "aliased output: the program still reads the buffer "
                    "after donation — drop the donation or restructure "
                    "so an output can reuse it",
                    detail={"arg": i, "shape": list(a["shape"]),
                            "dtype": a["dtype"]}))
        aliased = [(a["shape"], a["dtype"]) for i, a in enumerate(args)
                   if a["aliased_output"] is not None
                   or (compiled is not None and i in compiled)]
        for shape, dtype in ctx.extras.get("expect_aliased", ()):
            want = (tuple(shape), str(dtype))
            if want in aliased:
                aliased.remove(want)  # each expectation consumes one slot
                continue
            findings.append(self.finding(
                f"expected donated buffer {want[0]} {want[1]} (EF "
                "residual / pipeline double-buffer) is not covered by "
                "input_output_aliases — the sync state reallocates "
                "instead of updating in place",
                rule_id="GX-DONATE-002",
                detail={"shape": list(want[0]), "dtype": want[1]}))
        return findings


def audit_donation(fn: Callable, *args,
                   donate_argnums: Tuple[int, ...] = (),
                   expect_aliased: Sequence[Tuple[Sequence[int], str]] = (),
                   static_argnums: Tuple[int, ...] = ()) -> List[Finding]:
    """Lower ``fn`` with the given donation and run :class:`DonationPass`
    on the module text (suppressing jax's lowering-time warning — the
    pass reports the same fact as a structured finding).  Lowered with
    ``keep_unused=True`` so flat-argument positions stay 1:1 with the
    call signature and the donated set maps exactly."""
    import warnings

    import jax

    # map donated argnums to flattened argument positions (a pytree arg
    # contributes one flat position per leaf)
    pos = 0
    donated_positions = []
    for i, a in enumerate(args):
        nleaves = len(jax.tree.leaves(a))
        if i in donate_argnums:
            donated_positions.extend(range(pos, pos + nleaves))
        pos += nleaves
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        low = jax.jit(fn, donate_argnums=donate_argnums,
                      static_argnums=static_argnums,
                      keep_unused=True).lower(*args)
    ctx = AuditContext(lowered_text=low.as_text(),
                       extras={"expect_aliased": tuple(expect_aliased),
                               "donated_positions": donated_positions})
    return DonationPass().run(None, ctx)


# ---------------------------------------------------------------------------
# dtype-flow
# ---------------------------------------------------------------------------

class DtypeFlowPass(AuditPass):
    """GX-DTYPE-001: fp32 heavy-compute ops (dot/conv) on a path that
    declares 16-bit compute (``ctx.compute_dtype`` of "bfloat16" or
    "float16").  A leak burns double the MXU/HBM bandwidth the
    declaration promised and usually enters through one forgotten
    ``astype`` on a residual branch."""

    rule_id = "GX-DTYPE-001"

    def run(self, jaxpr, ctx: AuditContext) -> List[Finding]:
        declared = ctx.compute_dtype
        if declared not in ("bfloat16", "float16"):
            return []
        findings: List[Finding] = []
        for site in walk_jaxpr(jaxpr):
            if site.primitive not in _HEAVY_COMPUTE_PRIMS:
                continue
            op_dtypes = {aval_sig(v.aval)[1] for v in site.eqn.invars
                         if hasattr(v, "aval")}
            if "float32" in op_dtypes or "float64" in op_dtypes:
                findings.append(self.finding(
                    f"{site.primitive} computes in "
                    f"{sorted(op_dtypes & {'float32', 'float64'})} on a "
                    f"declared-{declared} path (fp32 leak)",
                    site=site,
                    detail={"operand_dtypes": sorted(op_dtypes)}))
        return findings


def audit_dtype_flow(fn: Callable, *args,
                     compute_dtype: str = "bfloat16") -> List[Finding]:
    """Trace ``fn`` and run the fp32-leak rule against the declared
    compute dtype."""
    import jax
    jx = jax.make_jaxpr(fn)(*args)
    return DtypeFlowPass().run(jx, AuditContext(compute_dtype=compute_dtype))


def audit_precision(fn: Callable, *args, precision: str = "bf16",
                    allowed_fp32_sites: int = 0) -> List[Finding]:
    """GX-DTYPE-001 for the first-class precision mode
    (``GEOMX_PRECISION``): audit a forward/loss closure built for
    ``precision`` and return the fp32 heavy-compute leaks.

    ``allowed_fp32_sites`` drops that many TRAILING findings before
    returning: the zoo's models intentionally compute the classifier
    head in fp32 (the last heavy op in the forward — softmax stability
    next to an fp32 loss), so a legitimately-built bf16 model audits
    clean with ``allowed_fp32_sites=1`` while a leak anywhere earlier
    in the network still surfaces.  ``precision="fp32"`` always returns
    [] (there is no declaration to violate)."""
    if str(precision).lower() in ("fp32", "float32", "f32"):
        return []
    findings = audit_dtype_flow(fn, *args, compute_dtype="bfloat16")
    if allowed_fp32_sites > 0:
        findings = findings[:-allowed_fp32_sites] \
            if len(findings) > allowed_fp32_sites else []
    return findings


def _traced_allreduce_jaxpr(compressor, params, num_parties: int = 2):
    """Trace ``compressor.allreduce`` over a ``num_parties``-wide dc
    mesh (virtual devices are fine: the jaxpr is platform-independent),
    returning the closed jaxpr.  The shared harness for the wire-
    accounting and purity audits."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from geomx_tpu.parallel.collectives import shard_map_compat
    from geomx_tpu.topology import DC_AXIS

    devs = jax.devices()
    if len(devs) < num_parties:
        raise RuntimeError(
            f"audit needs {num_parties} devices for the dc axis (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{num_parties})")
    mesh = Mesh(np.array(devs[:num_parties]), (DC_AXIS,))
    state = compressor.init_state(params)

    def f(gs, ss):
        g = jax.tree.map(lambda a: a[0], gs)
        s = jax.tree.map(lambda a: a[0], ss)
        out, s2 = compressor.allreduce(g, s, DC_AXIS, num_parties)
        return (jax.tree.map(lambda a: a[None], out),
                jax.tree.map(lambda a: a[None], s2))

    fn = shard_map_compat(f, mesh, in_specs=(P(DC_AXIS), P(DC_AXIS)),
                          out_specs=(P(DC_AXIS), P(DC_AXIS)))
    def stack(t):
        return jax.tree.map(
            lambda a: jnp.stack([jnp.asarray(a)] * num_parties), t)

    return jax.make_jaxpr(fn)(stack(params), stack(state))


# scatter-family primitives whose per-chip bytes differ from the
# "operand counts once" allreduce convention: a reduce_scatter
# (lax.psum_scatter) sends (N-1)/N of its full-size operand per chip,
# an all_gather forwards this chip's shard-size operand to N-1 peers.
# Both carry the mesh width in eqn.params["axis_size"].
_SCATTER_PRIMS = frozenset({"psum_scatter", "reduce_scatter"})
_GATHER_PRIMS = frozenset({"all_gather", "all_gather_invariant"})


def _collective_axis_size(eqn) -> Optional[int]:
    n = eqn.params.get("axis_size")
    try:
        return int(n) if n else None
    except (TypeError, ValueError):
        return None


def collective_wire_bytes(jaxpr, convention: str = "per_chip") -> int:
    """Bytes one participant puts on the wire per execution of the
    traced program, summed over its collectives' operands — the
    jaxpr-derived ground truth ``Compressor.wire_bytes`` must agree
    with.

    ``convention="per_chip"`` (default) counts physical bytes each chip
    sends per execution:

    - ``psum`` family: the operand counts once — the party's payload,
      the reference's ps-lite byte-counter convention;
    - ``psum_scatter`` / ``reduce_scatter``: the chip keeps its own 1/N
      shard, so it sends ``(N-1)/N`` of the full-size operand (the
      allreduce convention hard-coded here before the ZeRO path would
      overcount the kept shard);
    - ``all_gather``: the operand is this chip's shard and travels to
      every one of the N-1 peers, so it counts ``(N-1)`` times.

    ``N`` comes from the equation's ``axis_size`` param; a collective
    without one falls back to the operand-once convention.

    ``convention="payload"`` counts every collective operand exactly
    once — the N-independent per-party *contribution* convention that
    ``Compressor.wire_bytes`` declares (a psum's ring factor and a
    gather's (N-1) fan-out are transport properties, not payload)."""
    if convention not in ("per_chip", "payload"):
        raise ValueError(f"unknown wire-byte convention {convention!r}")
    total = 0.0
    for site in walk_jaxpr(jaxpr):
        if site.primitive not in COLLECTIVE_PRIMS:
            continue
        opb = sum(aval_bytes(v.aval) for v in site.eqn.invars
                  if hasattr(v, "aval"))
        n = _collective_axis_size(site.eqn)
        if convention == "payload":
            total += opb
        elif n and site.primitive in _SCATTER_PRIMS:
            total += opb * (n - 1) / n
        elif n and site.primitive in _GATHER_PRIMS:
            total += opb * (n - 1)
        else:
            total += opb
    return int(round(total))


def audit_wire_accounting(compressor, params, num_parties: int = 2,
                          rel_tol: float = 0.01,
                          abs_tol: int = 512) -> List[Finding]:
    """GX-DTYPE-002: diff ``compressor.wire_bytes(params)`` against the
    bytes the traced dc-tier collectives actually carry.  An accounting
    that under-reports hides wire cost from every telemetry consumer
    (``dc_compression_ratio``, byte counters, bench records); one that
    hardcodes fp32 for a 16-bit wire inflates it 2x.  Tolerances absorb
    lane padding (``abs_tol`` per program) and rounding.

    The diff runs under the *payload* convention (each collective
    operand once): ``wire_bytes`` documents the party's N-independent
    contribution, and an all_gather-emulated allreduce (bsc/fp16/2bit)
    fans that same payload to N-1 peers — per-chip counting would flag
    every honest gather-based compressor at ``num_parties > 2``.  A
    scatter+gather decomposition declared with the plain allreduce
    convention still trips the gate: its traced payload is the full
    operand plus the gathered shard, 1+1/N times the declared bytes."""
    jx = _traced_allreduce_jaxpr(compressor, params, num_parties)
    traced = collective_wire_bytes(jx, convention="payload")
    declared = int(compressor.wire_bytes(params))
    gap = abs(traced - declared)
    if gap <= abs_tol or gap <= rel_tol * max(traced, declared):
        return []
    return [Finding(
        rule_id="GX-DTYPE-002", severity="error",
        message=(f"wire accounting mismatch for compressor "
                 f"{compressor.name!r}: wire_bytes() declares {declared} "
                 f"B/party/step but the traced collectives carry "
                 f"{traced} B ({gap} B apart)"),
        detail={"declared": declared, "traced": traced,
                "compressor": compressor.name})]


# ---------------------------------------------------------------------------
# compressed-path purity
# ---------------------------------------------------------------------------

# scatter-family prims: the ops that MATERIALIZE a dense buffer from a
# sparse stream (the decompress).  The post-collective merge rule counts
# these — sort/cumsum stay out (they appear legitimately inside a later
# bucket's pre-collective select in multi-bucket programs)
_DENSIFY_PRIMS = frozenset({
    "scatter", "scatter-add", "scatter-mul", "scatter-min", "scatter-max"})


class PurityPass(AuditPass):
    """GX-PURITY-001, both sides of the compressed dc path:

    - *compress-before-collective* (the original rule): a collective
      operand whose byte size reaches ``ctx.dense_bytes`` (the dense
      fp32 footprint of the largest bucket/leaf the compressor covers)
      means a dense intermediate crossed select/pack and the collective
      (the decompress-before-collective regression class);
    - *merge-without-densify* (the post-collective side): after the
      FINAL collective, the merged sparse stream may densify at most
      ``ctx.extras["allowed_dense_after_collective"]`` times (default
      1 — the single final decompress).  A per-party densify-then-sum
      merge materializes one dense scatter per party and is flagged
      here even though its wire payloads were all compressed.  The
      anchor is the last collective (not every collective) so a later
      bucket's pre-collective select chain in a multi-bucket program
      never reads as "post-collective" of an earlier bucket.

    Reusable against any bucket size and both the jnp and fused paths:
    the fused kernels are opaque calls, so only genuinely wire-bound
    avals and true XLA scatters are inspected."""

    rule_id = "GX-PURITY-001"

    def run(self, jaxpr, ctx: AuditContext) -> List[Finding]:
        dense = ctx.dense_bytes
        if not dense:
            return []
        findings: List[Finding] = []
        sites = list(walk_jaxpr(jaxpr))
        last_collective = -1
        for i, site in enumerate(sites):
            if site.primitive not in COLLECTIVE_PRIMS:
                continue
            last_collective = i
            for v in site.eqn.invars:
                if not hasattr(v, "aval"):
                    continue
                nbytes = aval_bytes(v.aval)
                if nbytes >= dense:
                    shape, dtype = aval_sig(v.aval)
                    findings.append(self.finding(
                        f"{site.primitive} puts a dense-size operand "
                        f"({shape} {dtype}, {nbytes} B >= dense "
                        f"{dense} B) on the compressed dc path — a "
                        "dense intermediate leaked between select/pack "
                        "and the collective",
                        site=site,
                        detail={"bytes": nbytes, "dense_bytes": dense,
                                "shape": list(shape), "dtype": dtype}))
        if last_collective < 0:
            return findings
        allowed = int(ctx.extras.get("allowed_dense_after_collective", 1))
        densifies = 0
        for site in sites[last_collective + 1:]:
            if site.primitive not in _DENSIFY_PRIMS:
                continue
            for v in site.eqn.outvars:
                if not hasattr(v, "aval") or aval_bytes(v.aval) < dense:
                    continue
                densifies += 1
                if densifies > allowed:
                    shape, dtype = aval_sig(v.aval)
                    findings.append(self.finding(
                        f"{site.primitive} materializes dense output "
                        f"#{densifies} ({shape} {dtype}) after the final "
                        f"collective (allowed: {allowed}) — the merge "
                        "densifies per party instead of combining in "
                        "the compressed domain",
                        site=site,
                        detail={"densify_count": densifies,
                                "allowed": allowed,
                                "shape": list(shape), "dtype": dtype}))
        return findings


def _dense_floor_bytes(compressor, params) -> int:
    """The dense fp32 footprint of the largest unit the compressor
    sparsifies: the largest bucket for tree-fusing compressors, the
    largest sparse-eligible leaf otherwise (leaves below
    ``min_sparse_size``/``size_lower_bound`` legitimately go dense)."""
    import jax
    leaves = jax.tree.leaves(params)
    bucketer = getattr(compressor, "_bucketer", None)
    if callable(bucketer):
        bk = bucketer(leaves)
        if bk.bucket_sizes:
            return 4 * max(bk.bucket_sizes)
    floor = max((getattr(compressor, "min_sparse_size", 1),
                 getattr(compressor, "size_lower_bound", 1)))
    eligible = [leaf.size for leaf in leaves if leaf.size >= floor]
    return 4 * max(eligible) if eligible else 0


def audit_zero_compressed_path(bucketed, params, num_shards: int,
                               num_parties: int = 2) -> List[Finding]:
    """GX-PURITY-001 for the ZeRO dc tier (train/zero.py): trace the
    per-shard compressed allreduce (``BucketedCompressor.
    allreduce_shards``) over a dc mesh and require every wire payload to
    stay below the *shard*-dense floor — the shard path's stronger form
    of the purity claim: not only does no bucket-dense intermediate
    cross the wire, no chip even materializes one on the dc tier.
    Dense inner compressors are skipped like :func:`audit_compressed_path`."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from geomx_tpu.parallel.collectives import shard_map_compat
    from geomx_tpu.topology import DC_AXIS

    leaves = jax.tree.leaves(params)
    bk = bucketed.zero_bucketer(leaves)
    if not bk.bucket_sizes:
        return []
    shard_sizes = [n // num_shards for n in bk.bucket_sizes]
    dense_shard = 4 * max(shard_sizes)
    wire = int(bucketed.shard_wire_bytes(params, num_shards))
    if wire >= 4 * sum(shard_sizes):
        return []  # dense inner compressor: nothing to audit
    devs = jax.devices()
    if len(devs) < num_parties:
        raise RuntimeError(
            f"audit needs {num_parties} devices for the dc axis (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{num_parties})")
    mesh = Mesh(np.array(devs[:num_parties]), (DC_AXIS,))
    shards = [jnp.zeros((s,), jnp.float32) for s in shard_sizes]
    state = bucketed.init_shard_state(params, num_shards)

    def f(sh, ss):
        sh = [a[0] for a in sh]
        s = jax.tree.map(lambda a: a[0], ss)
        out, s2 = bucketed.allreduce_shards(sh, s, DC_AXIS, num_parties,
                                            bk)
        return ([a[None] for a in out],
                jax.tree.map(lambda a: a[None], s2))

    fn = shard_map_compat(f, mesh, in_specs=(P(DC_AXIS), P(DC_AXIS)),
                          out_specs=(P(DC_AXIS), P(DC_AXIS)))

    def stack(t):
        return jax.tree.map(
            lambda a: jnp.stack([jnp.asarray(a)] * num_parties), t)

    jx = jax.make_jaxpr(fn)(stack(shards), stack(state))
    return PurityPass().run(jx, AuditContext(dense_bytes=dense_shard))


def audit_compressed_path(compressor, params,
                          num_parties: int = 2) -> List[Finding]:
    """Trace the compressor's dc-tier allreduce over ``params`` and run
    :class:`PurityPass` with the dense floor derived from the
    compressor's own layout.  Dense compressors (``wire_bytes`` == dense
    fp32 bytes) are skipped — purity is a property of compressed paths."""
    import jax
    leaves = jax.tree.leaves(params)
    dense_fp32 = sum(leaf.size * 4 for leaf in leaves)
    wire = int(compressor.wire_bytes(params))
    if wire >= dense_fp32:
        return []  # dense path: nothing to audit
    dense_bytes = _dense_floor_bytes(compressor, params)
    if not dense_bytes:
        return []
    jx = _traced_allreduce_jaxpr(compressor, params, num_parties)
    # NOTE: device-local dense materializations (the jnp select chain's
    # cumsum/scatter) are legitimate here — the fused-path structural
    # claim that those ops are GONE from the lowered HLO lives in
    # analysis/hlo.py, not in this wire-purity rule.
    return PurityPass().run(jx, AuditContext(dense_bytes=dense_bytes))
