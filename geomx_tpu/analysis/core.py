"""Graft Auditor core: jaxpr walking, the pass framework, and findings.

Three PRs in a row hand-rolled one-off static checks — PR 4's "dense
scatter/cumsum ops are GONE from the lowered HLO" regression, PR 5's
byte-identical-jaxpr telemetry guarantee, bench's DCE-based collective
counting — because the correctness properties this system lives on are
*program-shape* properties, not runtime ones: every party must execute
the same collective sequence (or the mesh deadlocks/diverges silently,
the failure class "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" engineers against), the compressed path must
never put a dense payload on the WAN, and disabled subsystems must cost
zero ops.  This package makes those checks a real analysis layer: a
walker over traced jaxprs, passes producing structured ``Finding``s with
equation provenance, and a severity gate (``GEOMX_AUDIT`` /
``GEOMX_AUDIT_SEVERITY``) that turns findings into hard errors at the
recompile boundaries where mismatched programs are born.

Vocabulary:

- :class:`EqnSite`  — one equation plus its nesting path ("shard_map/
  pjit[3]") and index, yielded by :func:`walk_jaxpr`;
- :class:`Finding`  — rule id, severity, message, provenance;
- :class:`AuditPass` — ``run(closed_jaxpr, ctx) -> [Finding]``;
- :func:`run_passes` / :func:`enforce` — drive passes, gate severities.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

# severity order: gate "warning" admits warnings AND errors; "error"
# admits errors only.  "info" findings never raise.
SEVERITIES = ("info", "warning", "error")

# sub-jaxprs of these primitives run on-chip inside one opaque kernel
# launch (Mosaic); their internal equations are not XLA program shape and
# the walker treats the call itself as a leaf op.
OPAQUE_PRIMS = frozenset({"pallas_call"})


def _severity_rank(sev: str) -> int:
    try:
        return SEVERITIES.index(sev)
    except ValueError:
        raise ValueError(
            f"unknown severity {sev!r}: expected one of {SEVERITIES}")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One auditor result, with enough provenance to act on it."""

    rule_id: str                 # e.g. "GX-COLLECTIVE-001"
    severity: str                # "info" | "warning" | "error"
    message: str                 # human-readable, one line
    primitive: str = ""          # offending eqn's primitive name ("" = n/a)
    path: str = ""               # nesting path, e.g. "shard_map/pjit[12]"
    source: str = ""             # jax source_info summary when available
    detail: Optional[dict] = None  # rule-specific structured payload

    def __post_init__(self):
        _severity_rank(self.severity)  # validate eagerly

    def format(self) -> str:
        loc = self.path or "<program>"
        src = f" ({self.source})" if self.source else ""
        return f"[{self.rule_id}:{self.severity}] {loc}{src}: {self.message}"


@dataclasses.dataclass(frozen=True)
class EqnSite:
    """An equation with its provenance inside the (nested) jaxpr."""

    eqn: Any
    path: str     # "/"-joined nesting of enclosing call primitives
    index: int    # flattened walk order (stable across identical traces)

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name

    def source(self) -> str:
        """Best-effort one-line source provenance for the equation."""
        try:
            frame = self.eqn.source_info.traceback.frames[0]
            return f"{frame.file_name}:{frame.start_line}"
        except Exception:
            return ""


def _sub_jaxprs(eqn) -> Iterator[Any]:
    """Yield every jaxpr nested in an equation's params (pjit/scan jaxpr,
    cond branches, while cond/body, custom_jvp call_jaxpr, ...)."""
    for val in eqn.params.values():
        for sub in (val if isinstance(val, (list, tuple)) else (val,)):
            if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                yield getattr(sub, "jaxpr", sub)


def walk_jaxpr(jaxpr, enter_opaque: bool = False) -> Iterator[EqnSite]:
    """Depth-first walk over every equation of ``jaxpr`` (a Jaxpr or
    ClosedJaxpr), descending into nested jaxprs in deterministic trace
    order.  Equations inside :data:`OPAQUE_PRIMS` bodies (Pallas kernel
    jaxprs) are skipped unless ``enter_opaque`` — a kernel's internals
    are device microcode, not XLA program shape."""
    counter = [0]

    def _walk(core, path):
        core = getattr(core, "jaxpr", core)
        for eqn in core.eqns:
            yield EqnSite(eqn=eqn, path=path, index=counter[0])
            counter[0] += 1
            name = eqn.primitive.name
            if name in OPAQUE_PRIMS and not enter_opaque:
                continue
            sub_path = f"{path}/{name}" if path else name
            for sub in _sub_jaxprs(eqn):
                yield from _walk(sub, sub_path)

    yield from _walk(jaxpr, "")


def aval_bytes(aval) -> int:
    """HBM footprint of a shaped aval (0 for non-array avals)."""
    import numpy as np
    try:
        return int(aval.size) * int(np.dtype(aval.dtype).itemsize)
    except Exception:
        return 0


def aval_sig(aval) -> Tuple[Tuple[int, ...], str]:
    """(shape, dtype) signature of an aval, hashable and repr-stable."""
    try:
        return (tuple(int(d) for d in aval.shape), str(aval.dtype))
    except Exception:
        return ((), "?")


@dataclasses.dataclass
class AuditContext:
    """Per-audit metadata handed to passes.

    ``dense_bytes``: the dense fp32 footprint the compressed-path rules
    compare wire payloads against (largest bucket/leaf).  ``compute_dtype``:
    the declared 16-bit compute dtype for the dtype-flow pass (None
    disables the leak rule).  ``lowered_text``: StableHLO text for passes
    that read lowering-level facts (donation/aliasing).  ``extras`` is a
    free-form bag for rule-specific inputs.
    """

    dense_bytes: Optional[int] = None
    compute_dtype: Optional[str] = None
    lowered_text: Optional[str] = None
    label: str = ""
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)


class AuditPass:
    """Base class: one named rule family over a traced program."""

    rule_id: str = "GX-BASE-000"
    default_severity: str = "error"

    def run(self, jaxpr, ctx: AuditContext) -> List[Finding]:
        raise NotImplementedError

    def finding(self, message: str, site: Optional[EqnSite] = None,
                severity: Optional[str] = None, rule_id: Optional[str] = None,
                detail: Optional[dict] = None) -> Finding:
        return Finding(
            rule_id=rule_id or self.rule_id,
            severity=severity or self.default_severity,
            message=message,
            primitive=site.primitive if site is not None else "",
            path=(f"{site.path}[{site.index}]" if site is not None else ""),
            source=site.source() if site is not None else "",
            detail=detail)


def run_passes(jaxpr, passes: Sequence[AuditPass],
               ctx: Optional[AuditContext] = None) -> List[Finding]:
    """Run every pass over one traced program; findings concatenate in
    pass order (each pass's findings keep walk order)."""
    ctx = ctx or AuditContext()
    out: List[Finding] = []
    for p in passes:
        out.extend(p.run(jaxpr, ctx))
    return out


class AuditError(Exception):
    """Raised by :func:`enforce` when findings cross the severity gate.
    Carries the full finding list (``.findings``) so callers can log or
    rejudge — the message holds the formatted gate-crossing subset."""

    def __init__(self, findings: Sequence[Finding], gate: str):
        self.findings = list(findings)
        self.gate = gate
        over = [f for f in findings
                if _severity_rank(f.severity) >= _severity_rank(gate)]
        lines = "\n  ".join(f.format() for f in over)
        super().__init__(
            f"graft auditor: {len(over)} finding(s) at or above "
            f"severity {gate!r}:\n  {lines}")


def enforce(findings: Sequence[Finding], gate: str = "error") -> List[Finding]:
    """Raise :class:`AuditError` if any finding's severity reaches
    ``gate``; otherwise return the findings unchanged (callers log the
    sub-gate remainder)."""
    rank = _severity_rank(gate)
    if any(_severity_rank(f.severity) >= rank for f in findings):
        raise AuditError(findings, gate)
    return list(findings)


def summarize(findings: Sequence[Finding]) -> Dict[str, int]:
    """Finding counts per rule id (the shape bench --audit emits)."""
    out: Dict[str, int] = {}
    for f in findings:
        out[f.rule_id] = out.get(f.rule_id, 0) + 1
    return out


# ---------------------------------------------------------------------------
# the audit gate (config surface, mirroring telemetry_enabled)
# ---------------------------------------------------------------------------

def audit_enabled(config: Optional[Any] = None) -> bool:
    """The master auditor gate: ``config.audit`` or ``GEOMX_AUDIT``,
    parsed with the same numeric-boolean rules as every other GEOMX_*
    knob.  Static — read where audit hooks are *built* (Trainer init),
    so flipping it is a rebuild."""
    if config is not None and getattr(config, "audit", False):
        return True
    from geomx_tpu.config import _env_bool
    return _env_bool(["GEOMX_AUDIT"], False)


def audit_severity_gate(config: Optional[Any] = None) -> str:
    """The severity at which findings raise (``GEOMX_AUDIT_SEVERITY`` /
    ``GeoConfig.audit_severity``); below it they only log."""
    gate = None
    if config is not None:
        gate = getattr(config, "audit_severity", None)
    if not gate:
        from geomx_tpu.config import _env
        gate = _env(["GEOMX_AUDIT_SEVERITY"], "error", str)
    _severity_rank(gate)
    return gate
