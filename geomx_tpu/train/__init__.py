"""The SPMD training engine: state, step builder, high-level trainer."""

from geomx_tpu.train.state import TrainState, replicate_tree, unreplicate_tree
from geomx_tpu.train.step import (build_eval_step, build_train_step,
                                  make_loss_fn)
from geomx_tpu.train.trainer import Trainer
from geomx_tpu.train.zero import ZeroPlan

__all__ = ["TrainState", "ZeroPlan", "replicate_tree",
           "unreplicate_tree", "build_train_step", "build_eval_step",
           "make_loss_fn", "Trainer"]
