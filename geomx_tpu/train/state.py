"""Replica-local training state.

Design note (the central TPU-native choice of this framework): the
reference keeps parameters in per-process NDArrays — every worker, local
server and global server holds its own copy, and divergence between copies
is exactly what the sync algorithms manage (HFA lets workers drift for K1
steps; MixedSync serves stale weights).  The SPMD equivalent is
*device-local state with explicit replica axes*: every state leaf carries
leading axes ``[num_parties, workers_per_party]`` sharded
``P("dc", "worker")``, so each device owns precisely its own copy — same
total memory as XLA replication, but drift becomes expressible.  Sync
algorithms are then collectives that re-align slices of those axes.

Under FSA all copies stay bit-identical (the hierarchical all-reduce and
the deterministic optimizer guarantee it); ``unreplicate_tree`` takes copy
(0, 0) for eval/checkpoint, matching the reference reading weights from
rank 0.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from geomx_tpu.topology import DC_AXIS, WORKER_AXIS, HiPSTopology


class TrainState(struct.PyTreeNode):
    step: jax.Array          # scalar, replicated
    params: Any              # leaves [P, W, ...] sharded P(dc, worker)
    opt_state: Any
    model_state: Any         # non-trainable collections (BatchNorm stats)
    sync_state: Any          # sync-algorithm state (milestones, residuals, ...)


def state_specs() -> TrainState:
    """PartitionSpec prefix-tree matching TrainState for shard_map."""
    rep = P(DC_AXIS, WORKER_AXIS)
    return TrainState(step=P(), params=rep, opt_state=rep,
                      model_state=rep, sync_state=rep)


def replicate_tree(tree: Any, topology: HiPSTopology, mesh: Mesh) -> Any:
    """Broadcast every leaf to [P, W, *shape] with P(dc, worker) sharding.

    The broadcast is a zero-copy numpy view; device_put materializes one
    copy per device — identical footprint to plain replication.
    """
    sharding = NamedSharding(mesh, P(DC_AXIS, WORKER_AXIS))
    shape2 = (topology.num_parties, topology.workers_per_party)

    def rep(x):
        x = np.asarray(x)
        return jax.device_put(np.broadcast_to(x[None, None], shape2 + x.shape),
                              sharding)

    return jax.tree.map(rep, tree)


def unreplicate_tree(tree: Any) -> Any:
    """Copy (party 0, worker 0) of every leaf, for eval/checkpoint."""
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x))[0, 0], tree)
