"""The jitted SPMD training step.

One ``jax.jit(shard_map(...))`` program per configuration replaces the
reference's entire per-step dataflow — imperative forward/backward through
the dependency engine, engine-async kvstore push, PS-side merge at two
tiers, optimizer at the global server, and the pull back down
(SURVEY.md §3.2-3.4).  XLA sees compute and both collective tiers in one
graph and overlaps them (the latency-hiding the reference needed P3 and
engine threads for comes from the scheduler here).
"""

from __future__ import annotations

import contextlib
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from geomx_tpu.parallel.collectives import shard_map_compat
from geomx_tpu.sync.base import SyncAlgorithm
from geomx_tpu.telemetry import probes as _probes
from geomx_tpu.topology import DC_AXIS, SP_AXIS, WORKER_AXIS, HiPSTopology
from geomx_tpu.train.state import TrainState, state_specs


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def _norm_input(x: jax.Array) -> jax.Array:
    """Image inputs (uint8 or float, 0-255 scale) normalize to [0,1]
    on-device, preserving the historical convention for float-array
    callers; WIDE integer dtypes are token ids and pass through
    untouched (embeddings index them directly)."""
    if jnp.issubdtype(x.dtype, jnp.integer) and x.dtype != jnp.uint8:
        return x
    return x.astype(jnp.float32) / 255.0


def resolve_precision(config=None) -> str:
    """The compute precision for this build: ``"fp32"`` or ``"bf16"``.

    Static, resolved at build time like every other step-shaping knob
    (``GeoConfig(precision=...)`` wins; ``GEOMX_PRECISION`` covers
    config-less call sites).  bf16 means fp32 master weights with bf16
    activations/matmuls — the loss, the gradients and the optimizer
    state all stay fp32, which is why no loss scaling exists anywhere
    in this mode: nothing that accumulates ever leaves fp32, and bf16
    shares fp32's exponent range so activations cannot underflow the
    way fp16 activations do (docs/performance.md)."""
    if config is not None:
        raw = getattr(config, "precision", "fp32")
    else:
        import os
        # the knob IS routed through GeoConfig.from_env; this is the
        # fallback for callers without a config (get_model factories)
        # graftlint: disable=GXL006 — config-less surface
        raw = os.environ.get("GEOMX_PRECISION", "fp32")
    alias = {"fp32": "fp32", "float32": "fp32", "f32": "fp32",
             "bf16": "bf16", "bfloat16": "bf16"}
    key = str(raw).lower()
    if key not in alias:
        raise ValueError(
            f"unknown precision {raw!r}: expected 'fp32' or 'bf16' "
            "(GEOMX_PRECISION / GeoConfig.precision)")
    return alias[key]


def make_loss_fn(apply_fn: Callable, mutable_keys=("batch_stats",),
                 compute_dtype=None):
    """Standard classification loss closure over a flax apply_fn.

    Images arrive uint8 NHWC; normalization to [0,1] happens on-device so
    the host->device transfer stays 1 byte/pixel.

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) casts the normalized
    float inputs before the forward — the entry half of the bf16 mode;
    the models cast their own internals per-layer from the fp32 master
    params.  Integer token-id inputs pass through regardless.  The
    default (``None``) traces exactly the historical ops, keeping the
    disabled-path jaxpr byte-identical (tests/test_telemetry.py).
    """

    def loss_fn(params, model_state, x, y):
        x = _norm_input(x)
        if compute_dtype is not None and jnp.issubdtype(x.dtype,
                                                        jnp.floating):
            x = x.astype(compute_dtype)
        variables = {"params": params, **model_state}
        mut = [k for k in mutable_keys if k in model_state]
        if mut:
            logits, new_model_state = apply_fn(variables, x, train=True,
                                               mutable=mut)
        else:
            logits = apply_fn(variables, x, train=True)
            new_model_state = model_state
        loss = cross_entropy_loss(logits, y)
        return loss, (new_model_state, logits)

    return loss_fn


def build_train_step(loss_fn: Callable, tx: optax.GradientTransformation,
                     sync: SyncAlgorithm, topology: HiPSTopology, mesh: Mesh,
                     donate: bool = True, config=None,
                     sp_model: bool = False):
    """Build `train_step(state, x, y) -> (state, metrics)`.

    - state leaves carry [num_parties, workers_per_party] replica axes;
    - x, y are [num_parties, workers_per_party, local_batch, ...];
    - metrics are global means (replicated scalars).

    With ``config.multi_gps`` set, leaves >= ``config.bigarray_bound``
    elements take the MultiGPS ZeRO-1 path (reduce_scatter -> shard-local
    optimizer -> all_gather over the worker axis; the dc-tier collective
    moves only the shard).  Requires FSA and a state initialized with
    shard-shaped optimizer/compressor leaves (Trainer handles this).

    ``sp_model``: the model runs in-graph collectives over the sp axis
    (Trainer sets this from the model's ``sp_mode``).  Sequence
    parallelism is a MODEL property, not just a mesh one: only an
    sp-aware model may receive sequence-sharded inputs and needs its
    shard-path grads SUMMED over sp.  A plain model on an sp mesh keeps
    replicated inputs and computes identical grads on every sp device —
    redundant but correct (no reduction needed), never silently sliced
    images.
    """
    sync.bind_topology(topology)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    sp = getattr(topology, "sp_degree", 1) if sp_model else 1
    # in-graph telemetry probes (telemetry/probes.py): the gate is
    # STATIC — resolved here, at build time — and guards the single
    # probe call site below, so the disabled path traces a jaxpr
    # byte-identical to a build with telemetry excised (pinned by
    # tests/test_telemetry.py)
    telem = _probes.telemetry_enabled(config)
    # Graft Pilot control operands (control/, docs/control.md): the same
    # static-gate contract — when GEOMX_CONTROL is on, sync_state
    # carries a "control" subtree of traced scalar operands (the bsc
    # ratio scale) that the dc-tier compressors read through a
    # trace-time context; when off, nothing here traces and the jaxpr is
    # byte-identical to a controller-excised build (pinned by
    # tests/test_control.py)
    from geomx_tpu.control.actuators import control_enabled
    ctl_on = control_enabled(config)

    mgps = None
    if config is not None and getattr(config, "multi_gps", False):
        from geomx_tpu.parallel.multigps import MultiGPSPlan
        from geomx_tpu.sync.fsa import FSA
        from geomx_tpu.sync.pipeline import PipelinedSync
        if sync.live_parties is not None:
            # fail loudly (same contract as the FSA check below): the
            # ZeRO-1 path calls the dc compressor directly and its big
            # leaves live as worker-axis shards — a masked renormalized
            # mean over sharded leaves needs per-shard re-layout this PR
            # does not implement
            raise ValueError(
                "GEOMX_MULTI_GPS does not compose with a degraded "
                "membership mask (resilience/): disable multi_gps or "
                "run with every party live")
        if isinstance(sync, PipelinedSync):
            # fail loudly (same contract as the FSA check below): the
            # ZeRO-1 update consumes the dc-tier shard in-step by
            # construction (reduce_scatter -> shard-local optimizer ->
            # all_gather), so there is no next-step slot to double-buffer
            # the collective into
            raise ValueError(
                "GEOMX_MULTI_GPS does not compose with "
                "GEOMX_PIPELINE_DEPTH: the sharded update needs this "
                "step's dc-tier result before the optimizer can run; "
                "disable one of the two")
        if not isinstance(sync, FSA):
            # fail loudly: a user "running MultiGPS" must not silently get
            # a replicated update (VERDICT r1 weak #2)
            raise ValueError(
                "GEOMX_MULTI_GPS requires sync_mode=fsa: the ZeRO-1 "
                "sharded update lives in gradient space; param-space "
                f"algorithms ({sync.name}) do not compose with it")
        mgps = MultiGPSPlan(config.bigarray_bound, topology.workers_per_party)
        from geomx_tpu.compression.base import NoCompressor
        from geomx_tpu.compression.bucketing import BucketedCompressor
        from geomx_tpu.sync.dgt import DGTCompressor
        if isinstance(sync.dc_compressor, BucketedCompressor):
            # MultiGPS keeps PER-LEAF dc semantics: big leaves cross the
            # WAN as 1/W worker-axis shards while small leaves stay
            # replicated, and the Trainer initializes shard-shaped
            # per-leaf compressor state (mixed_example).  Fusing shard
            # and replicated leaves into one bucket would pool their
            # top-k budgets across tensors that live on different
            # layouts, so unwrap back to the inner compressor here.
            sync.dc_compressor = sync.dc_compressor.inner
        if isinstance(sync.worker_compressor, DGTCompressor):
            # DGT's state is one flat schedule for the WHOLE gradient
            # (sync/dgt.py tree-level path); the MultiGPS update needs
            # per-leaf compressor state because big leaves bypass the
            # worker compressor entirely.  DGT is a WAN transport — put
            # it on the dc tier (where sync/__init__.py wires it); an
            # ICI-tier deferral would save nothing anyway.
            raise ValueError(
                "GEOMX_MULTI_GPS does not compose with DGT as the "
                "worker-tier compressor; configure DGT on the dc tier "
                "(enable_dgt wraps the dc compressor)")
        if not isinstance(sync.worker_compressor, NoCompressor):
            import warnings
            # big leaves' worker-tier reduce is the psum_scatter itself
            # (already a 1/W wire saving per link); a configured worker
            # compressor applies only to the small replicated leaves, and
            # the user should know the big ones bypass it (ADVICE r2 #1)
            warnings.warn(
                "multi_gps: leaves >= bigarray_bound use the sharded "
                "psum_scatter reduce and BYPASS the worker-tier "
                f"compressor ({sync.worker_compressor.name}); it still "
                "applies to smaller leaves", stacklevel=2)

    zplan = None
    if config is not None and getattr(config, "zero", False):
        from geomx_tpu.compression.base import NoCompressor
        from geomx_tpu.train.zero import ZeroPlan
        if mgps is not None:
            # fail loudly (same contract as the other composition
            # checks): both modes shard the weight update — MultiGPS
            # per-leaf, ZeRO per-bucket — and stacking them would shard
            # a shard
            raise ValueError(
                "GEOMX_ZERO does not compose with GEOMX_MULTI_GPS: both "
                "shard the weight update over the worker axis (ZeRO per "
                "fused bucket, MultiGPS per big leaf); pick one")
        zplan = getattr(sync, "zero_plan", None)
        if zplan is None:
            # rejects HFA (no shard form) and a non-bucketed dc engine,
            # and re-aligns the bucket padding so every bucket splits
            # into W lane-aligned shards (must happen before the first
            # trace).  bind_zero returns a bound COPY — the caller's
            # instance is never mutated; the Trainer binds up front and
            # passes the bound algorithm in, so its membership
            # recompiles land here with the plan already attached and
            # reuse it instead of re-binding per mask
            zplan = ZeroPlan(topology.workers_per_party)
            sync = sync.bind_zero(zplan)
        wc = getattr(sync, "worker_compressor",
                     getattr(getattr(sync, "inner", None),
                             "worker_compressor", None))
        if wc is not None and not isinstance(wc, NoCompressor):
            import warnings
            # the worker-tier reduce IS the psum_scatter (already a 1/W
            # wire saving per ICI link); a configured worker compressor
            # never runs — same contract as MultiGPS's big leaves
            warnings.warn(
                "GEOMX_ZERO: the worker-tier reduce is the bucket "
                "psum_scatter; the configured worker compressor "
                f"({wc.name}) is bypassed", stacklevel=2)

    # fused optimizer apply (ops/optim_pallas.py): the same static-gate
    # contract — resolved here at build time, and with the gate off the
    # update path below traces exactly the historical per-leaf optax
    # chain, keeping the default jaxpr byte-identical
    from geomx_tpu.ops.optim_pallas import (fused_apply, fused_optim_enabled,
                                            fused_spec_of)
    fopt_spec = None
    fopt_bucketer = None
    fopt_interp = False
    if fused_optim_enabled(config):
        fopt_spec = fused_spec_of(tx)
        if fopt_spec is None:
            # fail loudly (same contract as the composition checks
            # above): a plain optax closure hides its hyperparameters,
            # and silently falling back would report fused numbers from
            # an unfused run
            raise ValueError(
                "GEOMX_FUSED_OPTIM requires an optimizer built by "
                "ops.optim_pallas.fused_optimizer (the kernels need the "
                "static hyperparameters a plain optax closure hides)")
        if mgps is not None:
            raise ValueError(
                "GEOMX_FUSED_OPTIM does not compose with GEOMX_MULTI_GPS: "
                "the mixed shard/replicated per-leaf layout does not "
                "flatten into uniform buckets; use GEOMX_ZERO for a "
                "sharded fused update")
        if zplan is None:
            from geomx_tpu.compression.bucketing import BucketedCompressor
            from geomx_tpu.sync.pipeline import PipelinedCompressor
            dc = getattr(sync, "dc_compressor",
                         getattr(getattr(sync, "inner", None),
                                 "dc_compressor", None))
            if isinstance(dc, PipelinedCompressor):
                dc = dc.inner
            if not isinstance(dc, BucketedCompressor):
                raise ValueError(
                    "GEOMX_FUSED_OPTIM requires the bucketed dc-tier "
                    "engine (GEOMX_BUCKET_BYTES > 0): the kernels apply "
                    "the update over the flat fp32 buckets")
            fopt_bucketer = dc.zero_bucketer
        # interpret mode off-TPU (CI, CPU meshes) — same resolution as
        # the compression kernels' pallas_supported path.
        # GEOMX_FUSED_OPTIM_INTERPRET overrides (=0 forces the native
        # Mosaic lowering: bench --compare-mfu uses it to cross-lower
        # the step for the DCE structure gate on a CPU host — such a
        # build LOWERS anywhere but only RUNS on TPU)
        import os as _os
        # graftlint: disable=GXL006 — build-time gate
        _ov = _os.environ.get("GEOMX_FUSED_OPTIM_INTERPRET")
        if _ov is None:
            fopt_interp = jax.default_backend() != "tpu"
        else:
            fopt_interp = _ov.strip().lower() not in ("0", "false", "")
        if zplan is not None:
            # the ZeRO shard-local update consumes the same kernels over
            # its 1/W bucket shards (train/zero.py reads these)
            zplan.fused_spec = fopt_spec
            zplan.fused_interpret = fopt_interp

    def _zero_sync_update(grads, params, opt_state, sync_state, step):
        """ZeRO (train/zero.py): reduce-scatter compressed buckets ->
        shard-local optimizer -> all_gather params.  The optimizer (and
        its state, allocated shard-shaped by Trainer.init_state) sees
        flat 1/W bucket shards; one all_gather per bucket rebuilds the
        replicated params for the next forward."""
        shard_g, sync_state = sync.sync_grad_shards(grads, params,
                                                    sync_state, step)
        params, opt_state = zplan.apply_shard_update(
            tx, shard_g, params, opt_state, WORKER_AXIS)
        # param-space hook still runs on the rebuilt replicated params
        # (MixedSync's stale-pull refresh)
        params, sync_state = sync.sync_params(params, sync_state, step)
        return params, opt_state, sync_state

    def _mgps_sync_update(grads, params, opt_state, sync_state, step):
        """MultiGPS: hierarchical reduce + optimizer with big leaves
        sharded 1/W across the worker axis (reference placement:
        src/kvstore/kvstore_dist.h:792-833)."""
        nw, np_ = topology.workers_per_party, topology.num_parties
        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_ws = treedef.flatten_up_to(sync_state["worker_comp"])
        widx = lax.axis_index(WORKER_AXIS)

        mixed_g, new_ws = [], []
        for p, g, ws in zip(flat_p, flat_g, flat_ws):
            if mgps.is_big(p.size):
                # the scatter IS the worker-tier reduce (and compression:
                # each link moves 1/W of the tensor)
                mixed_g.append(mgps.scatter_grad_leaf(g, WORKER_AXIS))
                new_ws.append(ws)
            else:
                g, ws = sync.worker_compressor.allreduce_leaf(
                    g, ws, WORKER_AXIS, nw)
                mixed_g.append(g / nw if nw > 1 else g)
                new_ws.append(ws)
        # dc tier on the mixed tree: big leaves cross the WAN as shards
        dc = sync.dc_compressor
        if getattr(dc, "fuses_tree", False):
            # EXPLICIT composition with tree-fusing compressors (tree-
            # level DGT): one schedule per layout group.  A single flat
            # schedule over the whole mixed tree ranks blocks that mix
            # worker-axis shard content (different per worker slot) with
            # replicated leaves, so its send decisions differ across
            # workers and replicated leaves' aggregates diverge within a
            # party.  The split keeps the replicated group's schedule a
            # function of replicated content only (see
            # MultiGPSPlan.split_mixed; state initialized group-wise by
            # Trainer.init_state).
            sizes = [p.size for p in flat_p]
            big, small = mgps.split_mixed(sizes, mixed_g)
            dst = sync_state["dc_comp"]
            big_s, small_s = dst["sharded"], dst["replicated"]
            if big:
                big, big_s = dc.allreduce(big, big_s, DC_AXIS, np_)
            if small:
                small, small_s = dc.allreduce(small, small_s, DC_AXIS, np_)
            mixed_g = treedef.unflatten(
                mgps.stitch_mixed(sizes, big, small))
            dstate = {"sharded": big_s, "replicated": small_s}
        else:
            mixed_g, dstate = dc.allreduce(
                treedef.unflatten(mixed_g), sync_state["dc_comp"],
                DC_AXIS, np_)
        if np_ > 1:
            mixed_g = jax.tree.map(lambda x: x / np_, mixed_g)

        mixed_p = treedef.unflatten([
            mgps.shard_param_leaf(p, widx) if mgps.is_big(p.size) else p
            for p in flat_p])
        updates, opt_state = tx.update(mixed_g, opt_state, mixed_p)
        new_mixed = optax.apply_updates(mixed_p, updates)
        params = treedef.unflatten([
            mgps.unshard_param_leaf(nm, p, WORKER_AXIS)
            if mgps.is_big(p.size) else nm
            for p, nm in zip(flat_p, treedef.flatten_up_to(new_mixed))])
        sync_state = {"dc_comp": dstate,
                      "worker_comp": treedef.unflatten(new_ws)}
        return params, opt_state, sync_state

    def _device_step(state: TrainState, x, y):
        def squeeze(t):
            return jax.tree.map(lambda a: a[0, 0], t)

        def expand(t):
            return jax.tree.map(lambda a: a[None, None], t)
        params = squeeze(state.params)
        opt_state = squeeze(state.opt_state)
        model_state = squeeze(state.model_state)
        sync_state = squeeze(state.sync_state)
        step = state.step
        xb, yb = x[0, 0], y[0, 0]

        ctl = None
        if ctl_on:
            # detach the control operands before the sync hooks (whose
            # state-threading rebuilds dicts and would drop foreign
            # keys) and open them as a trace-time context for the
            # compressors; they rejoin the output sync_state below so
            # host-side actuation rewrites them without a recompile
            from geomx_tpu.control.actuators import CONTROL_KEY
            sync_state = dict(sync_state)
            ctl = sync_state.pop(CONTROL_KEY, None)
            if ctl is None:
                raise ValueError(
                    "GEOMX_CONTROL is on but sync_state carries no "
                    "control operands: initialize the state with a "
                    "control-enabled Trainer (init_state adds the "
                    f"{CONTROL_KEY!r} subtree)")

        fwd_params = sync.forward_params(params, sync_state)
        (loss, (model_state, logits)), grads = grad_fn(
            fwd_params, model_state, xb, yb)

        if sp > 1:
            # sequence parallelism: each sp device back-propagated only
            # its sequence shard's path (the model's forward psum/
            # attention collectives ride the sp axis); the true gradient
            # is the SUM of the shard contributions.  After this, grads
            # are identical across sp and the dc/worker sync tiers see
            # one consistent replica per (party, worker).
            grads = lax.psum(grads, SP_AXIS)
            model_state = jax.tree.map(
                lambda a: lax.pmean(a, SP_AXIS)
                if jnp.issubdtype(a.dtype, jnp.floating) else a,
                model_state)

        # kept for the probes: this device's gradients before any
        # cross-party aggregation (pure aliases — no traced ops)
        raw_grads = grads
        synced_grads = None
        probe_ctx = _probes.inline_collection() if telem \
            else contextlib.nullcontext(None)
        if ctl is not None:
            from geomx_tpu.control.actuators import control_operands
            ctl_ctx = control_operands(ctl)
        else:
            ctl_ctx = contextlib.nullcontext(None)
        with probe_ctx as inline_sink, ctl_ctx:
            if mgps is not None:
                params, opt_state, sync_state = _mgps_sync_update(
                    grads, params, opt_state, sync_state, step)
            elif zplan is not None:
                # ZeRO: sync+update fuse like MultiGPS, and the synced
                # gradient exists only as this worker's shard — the
                # replicated-value probes are skipped rather than
                # misreporting one shard under a replicated out-spec
                params, opt_state, sync_state = _zero_sync_update(
                    grads, params, opt_state, sync_state, step)
            else:
                grads, sync_state = sync.sync_grads(grads, params,
                                                    sync_state, step)
                # only algorithms whose sync output is mesh-replicated
                # feed the replicated-value probes (HFA's identity
                # sync_grads keeps per-device gradients, and publishing
                # one shard's local value under a replicated out-spec
                # would silently misreport)
                if sync.grads_replicated_after_sync:
                    synced_grads = grads
                if fopt_spec is not None:
                    # fused apply: params and grads flatten onto the
                    # bucket layout the dc tier already defined
                    # (opt_state lives on the same layout —
                    # Trainer.init_state), one Pallas pass per bucket
                    flat_p, tdef = jax.tree.flatten(params)
                    bk = fopt_bucketer(flat_p)
                    new_pb, opt_state = fused_apply(
                        fopt_spec, bk.flatten(flat_p),
                        bk.flatten(tdef.flatten_up_to(grads)),
                        opt_state, interpret=fopt_interp)
                    params = tdef.unflatten(bk.unflatten(new_pb))
                else:
                    updates, opt_state = tx.update(grads, opt_state,
                                                   params)
                    params = optax.apply_updates(params, updates)
                params, sync_state = sync.sync_params(params, sync_state,
                                                      step)
            model_state, sync_state = sync.sync_model_state(model_state,
                                                            sync_state,
                                                            step)
        if ctl is not None:
            # operands pass through unchanged (actuation is host-side);
            # rejoining after the hooks keeps the state structure stable
            # whatever dicts the algorithm rebuilt
            from geomx_tpu.control.actuators import CONTROL_KEY
            sync_state = dict(sync_state, **{CONTROL_KEY: ctl})

        acc = jnp.mean(jnp.argmax(logits, -1) == yb)
        metrics = {"loss": loss, "accuracy": acc}
        # global mean over every worker for reporting
        if sp > 1:
            metrics = jax.lax.pmean(metrics, SP_AXIS)
        metrics = jax.lax.pmean(metrics, WORKER_AXIS)
        pw = sync.party_weight()
        if pw is None:
            metrics = jax.lax.pmean(metrics, DC_AXIS)
        else:
            # degraded membership: report the mean over SURVIVORS — a
            # dead party's loss/accuracy describes data that never
            # reached the aggregate
            metrics = jax.tree.map(
                lambda x: jax.lax.psum(x * pw, DC_AXIS) / sync.num_live,
                metrics)
        # step metadata: the live-party count baked into this traced
        # step (static — the membership epoch is a recompile boundary);
        # bench.py --compare-resilience reads it back as evidence that
        # degraded steps really ran the renormalized survivor mean
        metrics["num_live_parties"] = jnp.asarray(sync.num_live,
                                                  jnp.float32)
        if telem:
            # step-health probes ride the replicated metrics output
            # (every value is mesh-replicated by construction); the host
            # plane (Trainer fit loop) publishes them to the metric
            # registry and the event log
            metrics["telemetry"] = _probes.collect_step_probes(
                raw_grads, synced_grads, sync, sync_state, inline_sink,
                params)
            if ctl is not None:
                # the live ratio scale rides the probe dict so the
                # registry (and the controller's own sensors) see the
                # operand the step actually ran with — replicated by
                # construction (every device holds the same state copy)
                metrics["telemetry"]["control_ratio_scale"] = \
                    ctl["bsc_ratio_scale"]

        new_state = TrainState(
            step=step + 1,
            params=expand(params),
            opt_state=expand(opt_state),
            model_state=expand(model_state),
            sync_state=expand(sync_state),
        )
        return new_state, metrics

    specs = state_specs()
    batch_spec = P(DC_AXIS, WORKER_AXIS)
    x_spec = batch_spec
    if sp > 1:
        # token batches [P, W, B, L(, ...)]: the sequence dim shards
        # over sp; state and labels replicate across sp (grads are
        # psum'd back to consistency inside the step)
        x_spec = P(DC_AXIS, WORKER_AXIS, None, SP_AXIS)
    mapped = shard_map_compat(
        _device_step, mesh,
        in_specs=(specs, x_spec, batch_spec),
        out_specs=(specs, P()),
    )
    if donate:
        return jax.jit(mapped, donate_argnums=(0,))
    return jax.jit(mapped)


def build_eval_step(apply_fn: Callable):
    """Single-program eval on unreplicated params (any one device).
    Returns (eval_step, logits_fn); both share the one normalization
    convention (uint8 -> [0,1] on device)."""

    @jax.jit
    def logits_fn(params, model_state, x):
        x = _norm_input(x)
        variables = {"params": params, **model_state}
        return apply_fn(variables, x, train=False)

    @jax.jit
    def eval_step(params, model_state, x, y):
        logits = logits_fn(params, model_state, x)
        pred = jnp.argmax(logits, -1)
        return jnp.sum(pred == y), jnp.asarray(y.shape[0], jnp.int32)

    return eval_step, logits_fn
