"""High-level Trainer: the reference's examples/cnn*.py loop as a library.

Wires model + optimizer + sync algorithm + topology into a fit loop with
per-iteration metrics, mirroring the reference workload's observable output
("[Time t][Epoch e][Iteration i] Test Acc a", examples/cnn.py:129-131) and
its JSON measurement reporter (examples/utils.py:120-192).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from geomx_tpu.config import GeoConfig
from geomx_tpu.data.loader import GeoDataLoader
from geomx_tpu.sync import get_sync_algorithm
from geomx_tpu.sync.base import SyncAlgorithm
from geomx_tpu.topology import HiPSTopology
from geomx_tpu.train.state import (TrainState, replicate_tree,
                                   unreplicate_tree)
from geomx_tpu.train.step import build_eval_step, build_train_step, make_loss_fn
from geomx_tpu.utils.metrics import Measure


class Trainer:
    def __init__(self, model, topology: HiPSTopology,
                 optimizer: optax.GradientTransformation,
                 sync: Optional[SyncAlgorithm] = None,
                 config: Optional[GeoConfig] = None,
                 mesh=None, donate: bool = True,
                 single_device_model=None):
        """``single_device_model``: a twin of ``model`` with the same
        parameter structure but no in-graph collectives, used for the
        un-meshed paths (init, eval, predict).  Required when ``model``
        calls axis collectives (e.g. sequence-parallel attention over the
        sp axis), which only trace inside the sharded train step."""
        self.model = model
        self._sd_model = single_device_model or model
        self.topology = topology
        self.config = config or GeoConfig(
            num_parties=topology.num_parties,
            workers_per_party=topology.workers_per_party)
        self.sync = sync if sync is not None else get_sync_algorithm(self.config)
        self.mesh = mesh if mesh is not None else topology.build_mesh()
        self.tx = optimizer
        # compute precision (train/step.resolve_precision): under bf16
        # the loss closure casts the normalized float inputs and the
        # models cast their own internals per-op from the fp32 master
        # params — nothing that accumulates ever leaves fp32, so there
        # is no loss scaling to configure (docs/performance.md)
        from geomx_tpu.train.step import resolve_precision
        self._precision = resolve_precision(self.config)
        compute_dtype = jnp.bfloat16 if self._precision == "bf16" else None
        self.loss_fn = make_loss_fn(model.apply,
                                    compute_dtype=compute_dtype)
        if self._precision == "bf16":
            mdt = getattr(model, "dtype", None)
            if mdt is None or mdt == jnp.float32:
                import warnings
                # the input cast alone buys nothing if the model's
                # layers immediately promote back to fp32
                warnings.warn(
                    "GEOMX_PRECISION=bf16 but the model's compute dtype "
                    f"is {mdt!r}: its layers will promote back to fp32. "
                    "Build the model with a bf16 dtype (e.g. "
                    "get_model(name, precision='bf16')) to realize the "
                    "mixed-precision speedup", stacklevel=2)
        # fused optimizer apply (ops/optim_pallas.py): resolved here so
        # init_state allocates optimizer state on the bucket layout the
        # fused path updates; build_train_step re-checks the gate and
        # validates the optimizer/compressor stack
        from geomx_tpu.ops.optim_pallas import fused_optim_enabled
        self._fused_optim = fused_optim_enabled(self.config)
        # input-pipeline overlap depth (data/loader.py): how many
        # assembled+device_put batches the producer thread keeps in
        # flight ahead of the step; 0 = synchronous (the host_stall
        # baseline bench.py --compare-mfu measures against)
        self._prefetch = max(0, int(getattr(self.config, "prefetch", 2)))
        sp_model = getattr(model, "sp_mode", None) is not None
        if getattr(topology, "sp_degree", 1) > 1 and not sp_model:
            import warnings
            warnings.warn(
                f"topology has sp_degree={topology.sp_degree} but the "
                "model declares no sp_mode: inputs stay replicated over "
                "the sp axis and every sp device computes the same thing "
                "— correct but wasted chips. Use an sp-aware model (e.g. "
                "SeqClassifier(sp_mode='ring')) or sp_degree=1.",
                RuntimeWarning, stacklevel=2)
        self._sp_model = sp_model
        self._donate = donate
        # ZeRO-sharded weight update (train/zero.py, GEOMX_ZERO): bind
        # the plan HERE, onto the bound copy bind_zero returns, so the
        # trainer's own sync carries it (shard-shaped state init, the
        # sharded drain program, checkpoint/catch-up layout) and
        # build_train_step — including every membership recompile —
        # reuses one plan.  The caller's sync instance is never mutated.
        if getattr(self.config, "zero", False):
            if getattr(self.sync, "supports_zero", False) \
                    and self.sync.zero_plan is None:
                from geomx_tpu.train.zero import ZeroPlan
                self.sync = self.sync.bind_zero(
                    ZeroPlan(topology.workers_per_party))
        elif getattr(self.sync, "zero_plan", None) is not None:
            raise ValueError(
                "sync algorithm is ZeRO-bound (zero_plan set) but this "
                "trainer's config has zero=False: the step program would "
                "run the replicated update against shard-shaped sync "
                "state.  Pass a fresh (unbound) sync algorithm, or "
                "enable GEOMX_ZERO/GeoConfig(zero=True) to match")
        self.train_step = build_train_step(
            self.loss_fn, self.tx, self.sync, topology, self.mesh,
            donate=donate, config=self.config, sp_model=sp_model)
        # membership epochs (resilience/): the live-party mask currently
        # bound into self.train_step; None = every party live.  Each
        # distinct mask owns one compiled step program (the recompile
        # boundary), cached so a blackout/re-admit cycle compiles twice,
        # not per transition.
        self._membership: Optional[tuple] = None
        self._membership_version = 0
        self._step_cache = {None: self.train_step}
        self._mgps = None
        if self.config.multi_gps:
            from geomx_tpu.parallel.multigps import MultiGPSPlan
            self._mgps = MultiGPSPlan(self.config.bigarray_bound,
                                      topology.workers_per_party)
        # ZeRO-sharded weight update (train/zero.py, GEOMX_ZERO):
        # build_train_step bound the plan into the sync algorithm; the
        # Trainer needs it for shard-shaped state init, the sharded
        # drain program, and checkpoint/catch-up layout handling
        self._zero_plan = getattr(self.sync, "zero_plan", None)
        self._memory_gauge_published = False
        self.eval_step, self._logits_fn = build_eval_step(
            self._sd_model.apply)
        self._batch_sharding = topology.batch_sharding(self.mesh)
        self._drain_step = None       # lazily-built pipeline drain program
        self._epoch_runners: dict = {}
        self._eval_cache: dict = {}    # device-resident test set
        self._eval_sweeps: dict = {}   # batch_size -> scanned eval program
        # telemetry plane (docs/telemetry.md): when enabled, the fit
        # loop publishes the in-graph step probes to the metric registry
        # and the event log at the same boundaries it already syncs for
        # logging (no extra device round trips)
        from geomx_tpu.telemetry.probes import telemetry_enabled
        self._telemetry = telemetry_enabled(self.config)
        # Graft Pilot (control/, docs/control.md): when enabled, the
        # sync_state carries traced control operands (init_state adds
        # them) and apply_control is the actuation boundary — ratio
        # rewrites are operand swaps (no recompile), depth switches are
        # cached recompiles modeled on apply_membership
        from geomx_tpu.control.actuators import control_enabled
        self._control = control_enabled(self.config)
        self._control_cache: dict = {}   # (depth, membership) -> step_fn
        # graft auditor (analysis/, docs/analysis.md): when enabled, the
        # fit loop captures the active step program's collective
        # signature once (cheap: one abstract trace) and every
        # apply_membership recompile is diffed against it — a membership
        # mask must change CONSTANTS, never the collective sequence, or
        # live and recovering parties deadlock/diverge at the next epoch
        from geomx_tpu.analysis import audit_enabled, audit_severity_gate
        self._audit = audit_enabled(self.config)
        self._audit_gate = audit_severity_gate(self.config) \
            if self._audit else "error"
        self._audit_args = None     # (state, x, y) ShapeDtypeStructs
        self._audit_sigs: dict = {}  # membership key -> signature
        self._telem_last_it = 0
        # flight recorder (telemetry/flight.py, GEOMX_FLIGHT): a bounded
        # ring of per-step records with deterministic anomaly rules and
        # forensics auto-dumps, fed at the same publish boundaries as
        # the registry.  Rides the probes — without telemetry there is
        # nothing to record, so that misconfig warns instead of
        # silently recording empty rings.
        from geomx_tpu.telemetry.flight import (flight_recorder_from_config,
                                                install_incident_recorder)
        self._flight = flight_recorder_from_config(self.config)
        if self._flight is not None:
            # host-plane incidents (server/scheduler restarts, wire-CRC
            # rejections — notify_host_incident) land in the bounded
            # incident ring, so forensics bundles show recovery
            # activity next to the step records
            install_incident_recorder(self._flight)
        self._attr_window_us = None  # trace mark of the last flight window
        if self._flight is not None and not self._telemetry:
            import warnings
            warnings.warn(
                "GEOMX_FLIGHT is on but telemetry is off: the flight "
                "recorder rides the in-graph step probes — enable "
                "GEOMX_TELEMETRY/GeoConfig(telemetry=True) or the ring "
                "records nothing", RuntimeWarning, stacklevel=2)
        # run capsule (telemetry/capsule.py, GEOMX_CAPSULE): whole-run
        # observability capture — per-step sensor records at the same
        # publish boundary as the flight ring, the link journal via the
        # observatory tap, periodic registry samples, and the archive
        # written at every fit end (atomic; tools/runcap.py reads it)
        from geomx_tpu.telemetry.capsule import capsule_from_config
        self._capsule = capsule_from_config(self.config)
        if self._capsule is not None:
            from geomx_tpu.telemetry.links import get_link_observatory
            self._capsule.attach_observatory(get_link_observatory())
            self._capsule.sampler.start()
            # the sampler thread and the observatory tap must not
            # outlive the trainer: a process constructing many
            # capsule-armed trainers (repeated experiments, notebooks)
            # would otherwise leak one registry-walking daemon each.
            # The finalizer holds the capsule, never the trainer —
            # close_capsule() is the deterministic path.
            import weakref
            weakref.finalize(self, self._capsule.sampler.stop)
            weakref.finalize(self, self._capsule.detach_observatory)
            if not self._telemetry:
                import warnings
                warnings.warn(
                    "GEOMX_CAPSULE is on but telemetry is off: the "
                    "capsule's step records ride the published probes "
                    "— enable GEOMX_TELEMETRY/GeoConfig(telemetry="
                    "True) or the archive captures no sensor stream",
                    RuntimeWarning, stacklevel=2)
        self._event_log = None
        events_path = getattr(self.config, "telemetry_events", "")
        if events_path:
            from geomx_tpu.telemetry.export import (EventLog,
                                                    set_default_event_log)
            self._event_log = EventLog(events_path)
            # make this the process default too, so subsystems that only
            # know the global log_event() (membership transitions, relay
            # failures) land in the SAME file as the step probes
            set_default_event_log(self._event_log)

    def init_state(self, rng: jax.Array, sample_input: np.ndarray) -> TrainState:
        """sample_input: one local batch [b, H, W, C] (uint8 images) or
        [b, L] (integer token ids — passed through un-normalized)."""
        from geomx_tpu.train.step import _norm_input
        x0 = _norm_input(jnp.asarray(sample_input))
        # jit the init: one compiled program instead of thousands of eager
        # dispatches (critical on remote/tunneled devices)
        variables = jax.jit(
            lambda r, x: self._sd_model.init(r, x, train=False))(rng, x0)
        variables = dict(variables)
        params = variables.pop("params")
        model_state = variables  # batch_stats etc.
        if self._mgps is not None:
            # MultiGPS ZeRO-1: optimizer + compressor state for big leaves
            # is allocated per worker-axis shard (the 1/W memory saving);
            # every (dc, worker) slot then tracks only its own shard
            mixed = self._mgps.mixed_example(params)
            opt_state = self.tx.init(mixed)
            sync_state = self.sync.init_state(mixed, model_state=model_state)
            dc = getattr(self.sync, "dc_compressor", None)
            if dc is not None and getattr(dc, "fuses_tree", False):
                # tree-fusing dc compressors (tree-level DGT) run one
                # flat schedule per layout group under MultiGPS — shard
                # leaves and replicated leaves must not share blocks
                # (train/step.py _mgps_sync_update splits the same way)
                sizes = [leaf.size for leaf in jax.tree.leaves(params)]
                big, small = self._mgps.split_mixed(
                    sizes, jax.tree.leaves(mixed))
                sync_state = dict(sync_state, dc_comp={
                    "sharded": dc.init_state(big),
                    "replicated": dc.init_state(small)})
        elif self._zero_plan is not None:
            # ZeRO: the optimizer runs on flat 1/W bucket shards, so its
            # state is allocated shard-shaped — the per-chip memory
            # saving IS this allocation.  The sync algorithm's zero-
            # aware init sizes the dc-tier EF residuals the same way.
            shards = self._zero_plan.shard_example(
                params, self._zero_plan.bucketed)
            opt_state = self.tx.init(shards)
            sync_state = self.sync.init_state(params,
                                              model_state=model_state)
        elif self._fused_optim:
            # fused apply: the optimizer state lives on the flat bucket
            # layout (one fp32 vector per bucket, lane-padded sizes) —
            # the same layout the dc tier already fuses gradients onto,
            # so the kernels update params, moments and wire buckets in
            # one coordinate system
            from geomx_tpu.compression.bucketing import BucketedCompressor
            from geomx_tpu.sync.pipeline import PipelinedCompressor
            dc = getattr(self.sync, "dc_compressor",
                         getattr(getattr(self.sync, "inner", None),
                                 "dc_compressor", None))
            if isinstance(dc, PipelinedCompressor):
                dc = dc.inner
            if not isinstance(dc, BucketedCompressor):
                raise ValueError(
                    "GEOMX_FUSED_OPTIM requires the bucketed dc-tier "
                    "engine (GEOMX_BUCKET_BYTES > 0): the kernels apply "
                    "the update over the flat fp32 buckets")
            bk = dc.zero_bucketer(jax.tree.leaves(params))
            opt_state = self.tx.init(
                [jnp.zeros((n,), jnp.float32) for n in bk.bucket_sizes])
            sync_state = self.sync.init_state(params,
                                              model_state=model_state)
        else:
            opt_state = self.tx.init(params)
            sync_state = self.sync.init_state(params,
                                              model_state=model_state)
        if self._control:
            # control operands join sync_state so they ride the traced
            # step as INPUTS: retuning them is a host-side rewrite of
            # one scalar leaf, never a recompile (control/actuators.py)
            from geomx_tpu.control.actuators import (CONTROL_KEY,
                                                     init_control_operands)
            if not isinstance(sync_state, dict):
                raise ValueError(
                    "GEOMX_CONTROL needs a dict-shaped sync state to "
                    f"carry its operands; {self.sync.name!r} returns "
                    f"{type(sync_state).__name__}")
            sync_state = dict(sync_state)
            sync_state[CONTROL_KEY] = init_control_operands()
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params, opt_state=opt_state,
            model_state=model_state, sync_state=sync_state)
        # the replicated scalar must carry the SAME NamedSharding the
        # compiled step emits for it: a SingleDeviceSharding here makes
        # the second train_step/epoch-runner call a jit cache MISS (the
        # input sharding is part of the key) — one full recompile, ~10s
        # per process on a tunneled chip
        from jax.sharding import NamedSharding, PartitionSpec
        return TrainState(
            step=jax.device_put(state.step,
                                NamedSharding(self.mesh, PartitionSpec())),
            params=replicate_tree(state.params, self.topology, self.mesh),
            opt_state=replicate_tree(state.opt_state, self.topology, self.mesh),
            model_state=replicate_tree(state.model_state, self.topology, self.mesh),
            sync_state=replicate_tree(state.sync_state, self.topology, self.mesh),
        )

    def make_loader(self, x, y, batch_size: int, split_by_class: bool = False,
                    seed: int = 0, augment: bool = False,
                    device_cache: bool = False,
                    seq_sharded: Optional[bool] = None) -> GeoDataLoader:
        """``seq_sharded``: shard x's sequence dim over the sp axis
        (requires an sp topology).  Default: auto — wide-integer
        [N, L(, feat)] token batches on an sp topology; uint8 data
        (images) and floats keep plain replica sharding."""
        dtype = getattr(x, "dtype", None)
        ndim = getattr(x, "ndim", 0)
        if seq_sharded is None:
            seq_sharded = (
                getattr(self.topology, "sp_degree", 1) > 1
                and getattr(self.model, "sp_mode", None) is not None
                and dtype is not None
                and np.issubdtype(dtype, np.integer)
                and dtype != np.uint8 and ndim in (2, 3))
        sharding = self._batch_sharding
        if seq_sharded:
            sharding = (self.topology.seq_batch_sharding(self.mesh),
                        self._batch_sharding)
        return GeoDataLoader(x, y, self.topology, batch_size,
                             split_by_class=split_by_class, seed=seed,
                             sharding=sharding, augment=augment,
                             device_cache=device_cache)

    # ---- membership epochs (resilience/) ----------------------------------

    def apply_membership(self, state: TrainState, epoch,
                         policy: Optional[str] = None) -> TrainState:
        """Bind a new membership epoch (a ``MembershipEpoch`` or a
        live-party mask) — the recompile boundary of the resilience
        subsystem.

        Rebinds the sync algorithm to the mask, swaps ``train_step`` to
        the mask's compiled program (built on first use, cached after),
        and applies the residual policy to ``state.sync_state``:
        ``"reset"`` (default; ``GEOMX_RESILIENCE_RESIDUALS``) discards
        dc-tier error-feedback residuals and pipeline in-flight buffers
        accumulated under the old membership, ``"carry"`` keeps them
        (docs/resilience.md).  Returns the adjusted state; a no-op when
        the mask is unchanged.

        Re-admission: call :meth:`catchup_payload` for the state blob
        the returning party installs (``admit_party``) BEFORE this
        rebind widens the collective back over it."""
        from geomx_tpu.topology import normalize_live_mask
        mask = normalize_live_mask(getattr(epoch, "live_mask", epoch),
                                   self.topology.num_parties)
        key = None if all(mask) else mask
        if key == self._membership:
            return state
        if self._mgps is not None:
            raise ValueError(
                "GEOMX_MULTI_GPS does not compose with membership "
                "changes (resilience/): the ZeRO-1 shards have no "
                "renormalized-survivor form")
        if policy is None:
            # config-first, like every other knob: GeoConfig.from_env is
            # where GEOMX_RESILIENCE_RESIDUALS folds in, so an explicit
            # GeoConfig(resilience_residuals=...) must not be overridden
            # by a stale env var
            policy = getattr(self.config, "resilience_residuals",
                             None) or "reset"
        if policy not in ("reset", "carry"):
            # validate BEFORE any rebinding: a bad policy must not leave
            # the trainer half-switched to the new mask
            raise ValueError(f"unknown residual policy {policy!r}: "
                             "expected 'reset' or 'carry'")
        self.sync.bind_membership(mask)
        self._membership = key
        self._membership_version = getattr(epoch, "version",
                                           self._membership_version + 1)
        step_fn = self._step_cache.get(key)
        if step_fn is None:
            step_fn = build_train_step(
                self.loss_fn, self.tx, self.sync, self.topology,
                self.mesh, donate=self._donate, config=self.config,
                sp_model=self._sp_model)
            self._step_cache[key] = step_fn
        # graft auditor at the recompile boundary (GEOMX_AUDIT): the
        # new membership's program must trace the SAME ordered
        # collective sequence as the reference program — masking changes
        # constants, never collectives.  Raises AuditError (before the
        # swap) on divergence at/above the severity gate; call
        # apply_membership again after fixing the config to rebind.
        self._audit_membership_program(key, step_fn)
        self.train_step = step_fn
        # both close over the previous membership's traced program
        self._epoch_runners.clear()
        self._drain_step = None
        if self._zero_plan is not None and policy == "carry":
            # ZeRO + carry: the dc-tier state holds per-WORKER shard
            # content, which the (0, 0)-copy round trip below would
            # silently broadcast over every worker slot.  Carry is an
            # identity on sync state for every membership-capable
            # algorithm, so keep the device arrays untouched.
            return state
        # residual/buffer policy, applied host-side on copy (0, 0) and
        # re-replicated (sync state is identical across replicas for
        # every membership-capable algorithm; under ZeRO the reset
        # branch replaces the only worker-distinct subtree — dc_comp —
        # with freshly-initialized shard-shaped zeros, which broadcast
        # correctly)
        new_ss = self.sync.reset_comm_state(
            unreplicate_tree(state.params),
            unreplicate_tree(state.sync_state), policy)
        return TrainState(
            step=state.step, params=state.params,
            opt_state=state.opt_state, model_state=state.model_state,
            sync_state=replicate_tree(new_ss, self.topology, self.mesh))

    # ---- graft auditor (analysis/, docs/analysis.md) ----------------------

    def _step_signature(self, step_fn):
        """Collective signature + single-program consistency findings of
        a step program, traced on the abstract (ShapeDtypeStruct)
        reference arguments captured by the fit loop."""
        from geomx_tpu.analysis import (AuditContext,
                                        CollectiveConsistencyPass)
        st, xb, yb = self._audit_args
        ctx = AuditContext()
        findings = CollectiveConsistencyPass().run(
            jax.make_jaxpr(step_fn)(st, xb, yb), ctx)
        return ctx.extras["collective_signature"], findings

    def _audit_capture(self, state: TrainState, xb, yb) -> None:
        """Arm the auditor: record abstract step arguments and the
        active program's collective signature (once per Trainer; the
        first fit batch with GEOMX_AUDIT on).  One abstract trace — no
        compile, no device work."""
        if not self._audit or self._audit_args is not None:
            return
        self._audit_args = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (state, xb, yb))
        self._audit_sigs[self._membership] = self._step_signature(
            self.train_step)

    def _audit_membership_program(self, key, step_fn) -> None:
        """Audit the membership program about to be installed: its
        collective signature is diffed against the armed reference
        (divergence is GX-COLLECTIVE-002, always error severity — a
        program pair that deadlocks has no soft form) and the program's
        own consistency findings (e.g. the axis_index_groups warning)
        join in.  Findings at/above GEOMX_AUDIT_SEVERITY raise
        AuditError; below it they surface as warnings."""
        if not self._audit or self._audit_args is None:
            return
        cached = self._audit_sigs.get(key)
        if cached is None:
            cached = self._step_signature(step_fn)
            self._audit_sigs[key] = cached
        sig, prog_findings = cached
        ref_key, (ref, _) = next(iter(self._audit_sigs.items()))
        if key == ref_key:
            return
        from geomx_tpu.analysis import (diff_collective_signatures,
                                        enforce)
        findings = enforce(list(prog_findings) + diff_collective_signatures(
            {f"membership={ref_key}": ref, f"membership={key}": sig},
            rule_id="GX-COLLECTIVE-002"), self._audit_gate)
        if findings:  # below the gate: surface without stopping the run
            import warnings
            warnings.warn("\n".join(f.format() for f in findings),
                          RuntimeWarning, stacklevel=3)

    # ---- Graft Pilot actuation boundary (control/, docs/control.md) -------

    def _dc_ratio_compressor(self):
        """The ratio-bearing dc-tier compressor (BiSparse, possibly
        under MPQ), unwrapped through the Pipelined/Bucketed layers;
        None when the dc tier carries no top-k ratio."""
        dc = getattr(self.sync, "dc_compressor", None)
        if dc is None:
            dc = getattr(getattr(self.sync, "inner", None),
                         "dc_compressor", None)
        while dc is not None and not hasattr(dc, "ratio") \
                and hasattr(dc, "inner"):
            dc = dc.inner
        if dc is not None and not hasattr(dc, "ratio"):
            # MPQ routes large tensors to its BiSparse half
            dc = getattr(dc, "large", None)
        return dc if dc is not None and hasattr(dc, "ratio") else None

    def control_depth(self) -> int:
        """The pipeline depth currently compiled in (0 or 1)."""
        from geomx_tpu.sync.pipeline import PipelinedSync
        return 1 if isinstance(self.sync, PipelinedSync) else 0

    def apply_control(self, state: TrainState, decision) -> TrainState:
        """Apply one Graft Pilot decision — the control subsystem's
        actuation boundary (docs/control.md).

        - ``kind == "ratio"``: rewrite the ``bsc_ratio_scale`` operand
          in ``sync_state["control"]`` host-side with the SAME sharding
          the compiled step expects — the jit cache stays warm, no
          recompile (the bench pins the cached-executable count).
        - ``kind == "depth"``: wrap/unwrap ``PipelinedSync`` — a
          recompile boundary modeled on :meth:`apply_membership`
          (per-decision cached step programs; dc-tier error-feedback
          residuals CARRY across the swap, disabling drains the
          in-flight aggregate first so no gradient is lost; the
          collective-consistency audit re-runs on the new program
          before it is installed when GEOMX_AUDIT is armed).

        Relay decisions are host-plane only and never reach this
        method (``ControlActuator`` routes them to the transport).
        """
        if not self._control:
            raise ValueError(
                "apply_control needs GEOMX_CONTROL/GeoConfig(control="
                "True): the compiled step carries no control operands")
        kind = getattr(decision, "kind", None)
        if kind == "ratio":
            return self._apply_ratio(state, decision)
        if kind == "depth":
            return self._apply_depth(state, decision)
        raise ValueError(f"unknown control decision kind {kind!r}; "
                         "apply_control handles ratio | depth")

    def _apply_ratio(self, state: TrainState, decision) -> TrainState:
        from geomx_tpu.control.actuators import CONTROL_KEY
        comp = self._dc_ratio_compressor()
        if comp is None:
            raise ValueError(
                "ratio decision with no ratio-bearing dc compressor: "
                "configure bsc/mpq compression (the control scale tunes "
                "the top-k ratio)")
        target = float(decision.value)
        # the configured ratio is the wire CAPACITY — the traced scale
        # only selects below it (static shapes never change)
        scale = min(max(target / float(comp.ratio), 1e-6), 1.0)
        ctl = state.sync_state[CONTROL_KEY]
        leaf = ctl["bsc_ratio_scale"]
        new_leaf = jax.device_put(
            jnp.full(leaf.shape, scale, leaf.dtype), leaf.sharding)
        new_ctl = dict(ctl, bsc_ratio_scale=new_leaf)
        return TrainState(
            step=state.step, params=state.params,
            opt_state=state.opt_state, model_state=state.model_state,
            sync_state=dict(state.sync_state, **{CONTROL_KEY: new_ctl}))

    def _apply_depth(self, state: TrainState, decision) -> TrainState:
        import copy

        from geomx_tpu.control.actuators import CONTROL_KEY
        from geomx_tpu.sync.pipeline import PipelinedSync
        target = int(decision.value)
        if target not in (0, 1):
            raise ValueError(f"depth decision value must be 0 or 1 "
                             f"(got {decision.value!r})")
        current = self.control_depth()
        if target == current:
            return state
        if self._zero_plan is not None or self._mgps is not None:
            raise ValueError(
                "depth switching does not compose with GEOMX_ZERO/"
                "GEOMX_MULTI_GPS: their sharded updates re-layout the "
                "sync state this transition carries; pin the depth "
                "statically instead")
        if self.topology.num_parties <= 1:
            import warnings
            warnings.warn("depth decision ignored: num_parties=1 has "
                          "no dc-tier collective to pipeline",
                          RuntimeWarning, stacklevel=2)
            return state
        if target == 0:
            # land the in-flight aggregate BEFORE the swap: the parked
            # gradient applies exactly once, nothing is lost
            state = self.drain_pipeline(state)
        params0 = unreplicate_tree(state.params)
        ms0 = unreplicate_tree(state.model_state)
        old_ss = dict(unreplicate_tree(state.sync_state))
        ctl = old_ss.pop(CONTROL_KEY)
        if target == 1:
            new_sync = PipelinedSync(
                self.sync, dcasgd_lambda=self.config.pipeline_dcasgd)
        else:
            new_sync = copy.copy(self.sync.inner)
            # unwrap the PipelinedCompressor installed at wrap time; the
            # BucketedCompressor underneath (and its layout cache) is
            # shared, so no re-trace of the bucket layout
            new_sync.dc_compressor = self.sync.inner.dc_compressor.inner
        new_sync.bind_topology(self.topology)
        if self._membership is not None:
            new_sync.bind_membership(self._membership)
        # state transition with EF carry: the dc-tier error-feedback
        # residuals live at the same bucket coordinates on both sides of
        # the swap — discarding them would replay the parked mass as a
        # one-off gradient spike
        fresh = new_sync.init_state(params0, model_state=ms0)
        if target == 1:
            inner_fresh = dict(fresh["inner"])
            for key, val in old_ss.items():
                if key == "dc_comp":
                    inner_fresh["dc_comp"] = dict(
                        inner_fresh["dc_comp"], inner=val)
                elif key in inner_fresh:
                    inner_fresh[key] = val
            fresh = dict(fresh, inner=inner_fresh)
        else:
            old_inner = old_ss["inner"]
            fresh = dict(fresh)
            for key, val in old_inner.items():
                if key == "dc_comp":
                    fresh["dc_comp"] = val["inner"]
                elif key in fresh:
                    fresh[key] = val
        fresh[CONTROL_KEY] = ctl
        cache_key = (target, self._membership)
        step_fn = self._control_cache.get(cache_key)
        if step_fn is None:
            step_fn = build_train_step(
                self.loss_fn, self.tx, new_sync, self.topology,
                self.mesh, donate=self._donate, config=self.config,
                sp_model=self._sp_model)
            self._control_cache[cache_key] = step_fn
        new_state = TrainState(
            step=state.step, params=state.params,
            opt_state=state.opt_state, model_state=state.model_state,
            sync_state=replicate_tree(fresh, self.topology, self.mesh))
        # collective-signature audit across the swap (analysis/): the
        # new program's own cross-party consistency findings gate BEFORE
        # it is installed — a depth change legitimately changes the
        # collective sequence, so the diff-vs-reference check is
        # re-ARMED on the new program rather than diffed across depths
        if self._audit and self._audit_args is not None:
            _, xb_s, yb_s = self._audit_args
            self._audit_args = (jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                new_state), xb_s, yb_s)
            sig, findings = self._step_signature(step_fn)
            from geomx_tpu.analysis import enforce
            leftover = enforce(list(findings), self._audit_gate)
            if leftover:
                import warnings
                warnings.warn("\n".join(f.format() for f in leftover),
                              RuntimeWarning, stacklevel=2)
            self._audit_sigs = {self._membership: (sig, findings)}
        # install: the new sync owns the dc compressor stack from here;
        # membership/epoch caches built against the old program drop
        self.sync = new_sync
        self.train_step = step_fn
        self.config = dataclasses.replace(self.config,
                                          pipeline_depth=target)
        self._step_cache = {self._membership: step_fn}
        self._epoch_runners.clear()
        self._drain_step = None
        return new_state

    def catchup_payload(self, state: TrainState) -> bytes:
        """The re-admission catch-up blob: one unreplicated copy of the
        full TrainState (params, optimizer, model state AND sync state),
        serialized in the checkpoint tree format — what the surviving
        parties broadcast to a returning party before
        ``apply_membership`` widens the collective back over it.  Under
        ZeRO the shard-bearing fields keep the full worker axis (shard
        content differs per worker slot by design; copy (0, 0) would
        hand the returning party W copies of worker 0's shard)."""
        from geomx_tpu.resilience.liveness import pack_catchup
        if self._zero_plan is not None:
            from geomx_tpu.train.zero import host_zero_state
            return pack_catchup(host_zero_state(state))
        return pack_catchup(TrainState(
            step=np.asarray(jax.device_get(state.step)),
            params=unreplicate_tree(state.params),
            opt_state=unreplicate_tree(state.opt_state),
            model_state=unreplicate_tree(state.model_state),
            sync_state=unreplicate_tree(state.sync_state)))

    def admit_party(self, payload: bytes) -> TrainState:
        """Install a catch-up payload as this process's authoritative
        state (the returning party's half of the protocol): the inverse
        of :meth:`catchup_payload`, re-replicated with the same
        placement ``init_state`` uses (shard-aware under ZeRO)."""
        from jax.sharding import NamedSharding, PartitionSpec
        from geomx_tpu.resilience.liveness import unpack_catchup
        t = unpack_catchup(payload)
        if self._zero_plan is not None:
            from geomx_tpu.train.zero import place_zero_state
            return place_zero_state(t, self.topology, self.mesh)
        return TrainState(
            step=jax.device_put(jnp.asarray(t.step),
                                NamedSharding(self.mesh, PartitionSpec())),
            params=replicate_tree(t.params, self.topology, self.mesh),
            opt_state=replicate_tree(t.opt_state, self.topology, self.mesh),
            model_state=replicate_tree(t.model_state, self.topology,
                                       self.mesh),
            sync_state=replicate_tree(t.sync_state, self.topology,
                                      self.mesh))

    # ---- checkpointing (sharded-state aware; docs/api.md) ------------------

    def checkpoint_meta(self) -> dict:
        """The meta block a checkpoint of this trainer's state carries:
        whether the state is ZeRO-sharded and the topology it was
        sharded over, so :meth:`load_checkpoint` can re-shard onto a
        different worker count and reject a GEOMX_ZERO mismatch."""
        from geomx_tpu.train.zero import zero_checkpoint_meta
        return zero_checkpoint_meta(self._zero_plan, self.topology)

    def save_checkpoint(self, path: str, state: TrainState,
                        step=None) -> str:
        """Save ``state`` with this trainer's layout meta.  The device
        arrays keep their full ``[P, W, ...]`` replica axes, so a
        ZeRO run's per-worker shards are all captured (restoring onto
        the same topology is bit-exact, including mid-pipeline
        buffers)."""
        from geomx_tpu.utils.checkpoint import save_checkpoint
        return save_checkpoint(path, state, step=step,
                               meta=self.checkpoint_meta())

    def load_checkpoint(self, path: str, template: TrainState) -> TrainState:
        """Restore a checkpoint into this trainer.

        ``template`` is a state with this trainer's structure and
        placements (fresh ``init_state`` output).  Rules:

        - the checkpoint's ZeRO flag must match this trainer's
          ``GEOMX_ZERO`` — a sharded optimizer cannot be installed into
          a replicated update (or vice versa) and the mismatch raises
          with the fix spelled out;
        - same topology: leaves re-place directly (bit-exact resume,
          mid-pipeline buffers included);
        - different worker count (e.g. saved on 2x4, restored onto
          2x2): shard-bearing leaves are gathered into full flat
          buckets and re-split for the new worker axis
          (train/zero.py ``reshard_zero_state``)."""
        from geomx_tpu.utils.checkpoint import load_checkpoint
        host_state, meta = load_checkpoint(path, with_meta=True)
        ck_zero = bool((meta or {}).get("zero", False))
        if ck_zero != (self._zero_plan is not None):
            have = "GEOMX_ZERO=1" if ck_zero else "GEOMX_ZERO=0 (replicated)"
            want = "GEOMX_ZERO=1" if self._zero_plan is not None \
                else "GEOMX_ZERO=0 (replicated)"
            raise ValueError(
                f"checkpoint at {path!r} was saved with {have} but this "
                f"trainer runs {want}: the optimizer-state layouts are "
                "incompatible (sharded flat buckets vs replicated "
                "leaves).  Restore with a matching GEOMX_ZERO setting, "
                "or re-save from a trainer in the target mode")
        topo_meta = (int((meta or {}).get("num_parties",
                                          self.topology.num_parties)),
                     int((meta or {}).get("workers_per_party",
                                          self.topology.workers_per_party)))
        here = (self.topology.num_parties, self.topology.workers_per_party)
        if not ck_zero or topo_meta == here:
            # same layout: direct re-placement onto the template's
            # shardings (bit-exact)
            from geomx_tpu.utils.checkpoint import place_like
            return place_like(host_state, template)
        from geomx_tpu.train.zero import reshard_zero_state
        return reshard_zero_state(host_state, template, self.mesh)

    def drain_pipeline(self, state: TrainState) -> TrainState:
        """Apply a pipelined sync algorithm's completed in-flight dc-tier
        aggregate without feeding a new batch (sync/pipeline.py): with
        ``GEOMX_PIPELINE_DEPTH=1`` the last launched collectives have not
        been applied when training stops — call this after the final
        ``fit`` (before export/eval) so the last batch's gradient AND its
        model-state (BatchNorm) aggregate land.  The mirror of the
        pipeline's warmup bubble (the first step applies a zero aggregate
        while the buffer fills).  No-op for synchronous algorithms; the
        drained gradient buffer is zeroed (a subsequent ``fit`` warms up
        again) and the model-state buffer keeps the applied value, the
        same seeding a fresh init gets."""
        sync = self.sync
        if not hasattr(sync, "drain_grads"):
            return state
        if self._drain_step is None:
            from geomx_tpu.parallel.collectives import shard_map_compat
            from geomx_tpu.topology import WORKER_AXIS
            from geomx_tpu.train.state import state_specs
            tx = self.tx
            zplan = self._zero_plan

            def _drain(st):
                def squeeze(t):
                    return jax.tree.map(lambda a: a[0, 0], t)

                def expand(t):
                    return jax.tree.map(lambda a: a[None, None], t)
                params = squeeze(st.params)
                opt_state = squeeze(st.opt_state)
                model_state = squeeze(st.model_state)
                sync_state = squeeze(st.sync_state)
                if zplan is not None:
                    # ZeRO drain: apply the parked shard aggregates to
                    # this worker's param shards, then the same
                    # all_gather the step runs rebuilds full params —
                    # the buffers hold reduced values, so the gather is
                    # the drain's only collective
                    g_sh, sync_state = sync.drain_grad_shards(params,
                                                              sync_state)
                    params, opt_state = zplan.apply_shard_update(
                        tx, g_sh, params, opt_state, WORKER_AXIS)
                else:
                    # no collectives: the buffers already hold reduced
                    # values
                    g, sync_state = sync.drain_grads(params, sync_state)
                    updates, opt_state = tx.update(g, opt_state, params)
                    params = optax.apply_updates(params, updates)
                model_state, sync_state = sync.drain_model_state(
                    model_state, sync_state)
                return TrainState(step=st.step, params=expand(params),
                                  opt_state=expand(opt_state),
                                  model_state=expand(model_state),
                                  sync_state=expand(sync_state))

            specs = state_specs()
            self._drain_step = jax.jit(shard_map_compat(
                _drain, self.mesh, in_specs=(specs,), out_specs=specs))
        return self._drain_step(state)

    def _publish_telemetry(self, telem: dict, iteration: int,
                           stacked: bool = False) -> None:
        """Publish one step's probe dict (already device_get) to the
        metric registry + event log.  ``stacked=True``: the values carry
        a leading scan dimension (epoch runner) — publish the last step.
        Scalars become ``geomx_step_probe{probe=...}`` gauges, per-party
        vectors ``geomx_step_probe_party{probe=...,party=...}``; the
        static wire accounting also feeds monotonic byte/step counters
        (delta-scaled by the steps since the last publish, so counter
        rates stay honest at any log_every)."""
        from geomx_tpu.telemetry import get_registry, log_event
        reg = get_registry()
        fam = reg.gauge("geomx_step_probe",
                        "Latest published in-graph step probe", ("probe",))
        fam_p = reg.gauge("geomx_step_probe_party",
                          "Latest per-party in-graph step probe",
                          ("probe", "party"))
        flat: dict = {}
        for name, val in telem.items():
            arr = np.asarray(val)
            if stacked and arr.ndim >= 1:
                arr = arr[-1]
            if arr.ndim == 0:
                flat[name] = float(arr)
                fam.labels(probe=name).set(float(arr))
            elif arr.ndim == 1:
                flat[name] = [float(v) for v in arr]
                for p, v in enumerate(arr):
                    fam_p.labels(probe=name, party=str(p)).set(float(v))
        steps = iteration - self._telem_last_it
        if steps > 0:
            reg.counter("geomx_train_steps_total",
                        "Training steps published").inc(steps)
            if "dc_wire_bytes" in flat:
                reg.counter(
                    "geomx_dc_wire_bytes_total",
                    "dc-tier bytes put on the wire per party"
                ).inc(flat["dc_wire_bytes"] * steps)
            self._telem_last_it = iteration
        dc = getattr(self.sync, "dc_compressor", None)
        if dc is None:  # PipelinedSync wraps the algorithm that has it
            dc = getattr(getattr(self.sync, "inner", None),
                         "dc_compressor", None)
        while dc is not None and not hasattr(dc, "layout_summary") \
                and hasattr(dc, "inner"):
            dc = dc.inner  # unwrap Pipelined/DGT wrappers to the bucketer
        layout = getattr(dc, "layout_summary", None)
        layout = layout() if callable(layout) else None
        if layout:
            reg.gauge("geomx_bucket_count",
                      "dc-tier fused buckets per step").set(
                layout["num_buckets"])
            reg.gauge("geomx_bucket_pad_fraction",
                      "Lane-padding waste in the bucket layout").set(
                layout["pad_fraction"])
            if self._zero_plan is not None:
                # ZeRO bucket-shard layout: what one chip actually owns
                # (the memory claim's denominator, scraped instead of
                # bench-only)
                w = self._zero_plan.W
                reg.gauge("geomx_zero_workers",
                          "Worker-axis width the weight update is "
                          "sharded over").set(w)
                reg.gauge("geomx_zero_shard_elems",
                          "Flat bucket elements owned per chip under "
                          "the ZeRO-sharded update").set(
                    layout["padded_elems"] / w)
        if self._zero_plan is not None:
            reg.gauge("geomx_zero_enabled",
                      "1 when the ZeRO-sharded weight update is "
                      "active").set(1.0)
        if self._event_log is not None:
            self._event_log.emit("step_probes", iteration=iteration,
                                 **flat)
        else:
            log_event("step_probes", iteration=iteration, **flat)
        if self._capsule is not None:
            # record the sensor surface the way a control tick reads it
            # (registry gauge families) — what makes the capsule's
            # replayed observation stream bit-identical to the live one
            self._capsule.record_step(iteration)
        if self._flight is not None:
            fired = self._flight.record(
                iteration, flat,
                membership_version=self._membership_version,
                phases=self._attribution_phases())
            if fired:
                ev = dict(iteration=iteration, fired=fired,
                          bundle=(self._flight.dumps[-1]
                                  if self._flight.dumps else None))
                if self._event_log is not None:
                    self._event_log.emit("flight_anomaly", **ev)
                else:
                    log_event("flight_anomaly", **ev)

    def _attribution_phases(self) -> Optional[dict]:
        """Phase-fraction summary of the ``train/step`` spans the host
        profiler recorded since the previous publish boundary (None when
        the profiler is off or no step span landed in the window) — the
        ``phases`` feed the flight recorder's exposed_comms_jump rule
        watches.  Advances the window mark so consecutive publishes see
        disjoint span windows."""
        from geomx_tpu.utils.profiler import get_profiler
        prof = get_profiler()
        if not prof.running:
            return None
        from geomx_tpu.telemetry.attribution import attribute_trace
        att = attribute_trace(prof.to_doc(), since_us=self._attr_window_us)
        self._attr_window_us = prof.now_us()
        if not att["num_steps"]:
            return None
        return att["summary"]

    def step_memory_stats(self, state: TrainState, xb, yb):
        """Compiled-step memory accounting from XLA's
        ``compiled.memory_analysis()`` — the measured source for the
        ``geomx_step_memory_bytes`` gauge and bench ``--compare-zero``'s
        memory claim.  Adds the sharded-state accounting (bytes of
        optimizer + sync state one chip holds, from the placed arrays'
        shapes) so the 1/W claim is checkable even where the backend
        offers no analysis object."""
        n_dev = max(1, len(self.mesh.devices.reshape(-1)))

        def _per_chip_bytes(tree):
            return sum(leaf.size * leaf.dtype.itemsize
                       for leaf in jax.tree.leaves(tree)
                       if hasattr(leaf, "size")) / n_dev

        out = {
            "opt_state_bytes_per_chip": _per_chip_bytes(state.opt_state),
            "sync_state_bytes_per_chip": _per_chip_bytes(state.sync_state),
            "params_bytes_per_chip": _per_chip_bytes(state.params),
        }
        try:
            ma = self.train_step.lower(state, xb, yb).compile() \
                .memory_analysis()
        except Exception as e:  # backend without AOT memory stats
            out["memory_analysis"] = {"unavailable": repr(e)}
            return out
        if ma is None:
            out["memory_analysis"] = {"unavailable": "None"}
            return out
        fields = {}
        for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
            if hasattr(ma, k):
                fields[k] = int(getattr(ma, k))
        fields["step_memory_bytes"] = (
            fields.get("temp_size_in_bytes", 0)
            + fields.get("argument_size_in_bytes", 0)
            + fields.get("output_size_in_bytes", 0))
        out["memory_analysis"] = fields
        return out

    def publish_memory_metrics(self, state: TrainState, xb, yb) -> None:
        """Publish the per-chip step-memory gauges (telemetry plane;
        once per trainer — the program is static).  One extra AOT
        lower+compile; only runs when telemetry is enabled."""
        if self._memory_gauge_published:
            return
        self._memory_gauge_published = True
        from geomx_tpu.telemetry import get_registry
        stats = self.step_memory_stats(state, xb, yb)
        reg = get_registry()
        fam = reg.gauge("geomx_step_memory_bytes",
                        "Per-chip training-step memory by component",
                        ("component",))
        for comp in ("opt_state_bytes_per_chip",
                     "sync_state_bytes_per_chip",
                     "params_bytes_per_chip"):
            fam.labels(component=comp.replace("_bytes_per_chip", "")) \
                .set(float(stats[comp]))
        ma = stats.get("memory_analysis", {})
        if "step_memory_bytes" in ma:
            fam.labels(component="compiled_step").set(
                float(ma["step_memory_bytes"]))

    def predict_logits(self, state: TrainState, x: np.ndarray,
                       batch_size: int = 512) -> np.ndarray:
        """Jitted logits over a host array (one device, unreplicated
        params); the single eval path Module.predict/score also use."""
        params = jax.tree.map(lambda a: a[0, 0], state.params)
        model_state = jax.tree.map(lambda a: a[0, 0], state.model_state)
        outs = []
        for i in range(0, len(x), batch_size):
            xb = x[i:i + batch_size]
            pad = batch_size - len(xb)
            if pad:  # pad the ragged tail: one compiled shape only
                xb = np.concatenate(
                    [xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
            logits = np.asarray(self._logits_fn(params, model_state,
                                                jnp.asarray(xb)))
            outs.append(logits[:batch_size - pad] if pad else logits)
        return np.concatenate(outs) if outs else np.zeros((0,))

    def evaluate(self, state: TrainState, x: np.ndarray, y: np.ndarray,
                 batch_size: int = 512) -> float:
        """Test accuracy over (x, y): the dataset is cached on device on
        first use and the whole sweep runs as ONE scanned program — one
        dispatch and one scalar readback per call, instead of a host
        round trip per batch (which dominates eval wall-clock on a
        remote/tunneled chip)."""
        n = len(x)
        # content-fingerprint cache key (not object identity, which a
        # recycled id or in-place mutation would silently go stale on):
        # all of y plus x strided down to <= ~4 MB.  A mutation confined
        # to skipped x elements can evade the fingerprint; per-epoch eval
        # sets are static in practice.
        import hashlib
        xa, ya = np.ascontiguousarray(x), np.ascontiguousarray(y)
        stride = max(1, xa.nbytes // (4 << 20))
        fp = hashlib.md5(xa[::stride].tobytes() + ya.tobytes()).hexdigest()
        cache_key = (xa.shape, fp, batch_size)
        cached = self._eval_cache.get(cache_key)
        if cached is None:
            pad = (-n) % batch_size
            xp = np.concatenate(
                [xa, np.zeros((pad,) + xa.shape[1:], xa.dtype)]) \
                if pad else xa
            yp = np.concatenate(
                [ya, np.full((pad,), -1, ya.dtype)]) if pad else ya
            cached = (jax.device_put(xp), jax.device_put(yp))
            if len(self._eval_cache) >= 2:  # 2-slot LRU: train+test sets
                self._eval_cache.pop(next(iter(self._eval_cache)))
            self._eval_cache[cache_key] = cached
        else:  # refresh LRU order
            self._eval_cache[cache_key] = self._eval_cache.pop(cache_key)
        dx, dy = cached

        run = self._eval_sweeps.get(batch_size)
        if run is None:
            eval_step = self.eval_step
            b = batch_size

            @jax.jit
            def run(params, model_state, dx, dy):
                # copy (0, 0) selection happens IN-program: eager
                # per-leaf slicing was ~2 host dispatches per leaf per
                # call — hundreds of tunnel round trips per eval
                params = jax.tree.map(lambda a: a[0, 0], params)
                model_state = jax.tree.map(lambda a: a[0, 0], model_state)

                def body(acc, i):
                    xb = jax.lax.dynamic_slice_in_dim(dx, i * b, b)
                    yb = jax.lax.dynamic_slice_in_dim(dy, i * b, b)
                    c, _ = eval_step(params, model_state, xb, yb)
                    return acc + c, None
                acc, _ = jax.lax.scan(body, jnp.zeros((), jnp.int32),
                                      jnp.arange(dx.shape[0] // b))
                return acc

            self._eval_sweeps[batch_size] = run
        correct = int(run(state.params, state.model_state, dx, dy))
        return correct / max(n, 1)

    def _epoch_runner(self, loader: GeoDataLoader):
        """One-dispatch-per-epoch runner: lax.scan over the epoch's steps
        with on-device batch gather/augment inside the program.  With a
        device-cached dataset this removes every per-step host round trip
        — the strongest form of the input/compute overlap the reference
        builds from engine threads + prefetching iterators.  Cached by
        (augment, pad) — the only loader-dependent trace inputs — so the
        closure never pins a loader (or its HBM dataset) in memory."""
        # honor the loader's x/y split (sp topologies shard x's sequence
        # dim over the sp axis while labels stay on the replica grid);
        # the shardings join the cache key so loaders with different
        # layouts don't share a traced runner
        x_sharding = getattr(loader, "x_sharding", self._batch_sharding)
        y_sharding = getattr(loader, "y_sharding", self._batch_sharding)
        cache_key = (loader.augment, loader.pad, x_sharding, y_sharding)
        run = self._epoch_runners.get(cache_key)
        if run is not None:
            return run
        from geomx_tpu.data.loader import gather_batch
        step_fn = self.train_step
        augment, pad = loader.augment, loader.pad

        import functools

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run(state, dx, dy, sel, key):
            def body(st, inp):
                s, i = inp
                xb, yb = gather_batch(dx, dy, s, jax.random.fold_in(key, i),
                                      augment=augment, pad=pad)
                if x_sharding is not None:
                    xb = jax.lax.with_sharding_constraint(xb, x_sharding)
                    yb = jax.lax.with_sharding_constraint(yb, y_sharding)
                return step_fn(st, xb, yb)
            return jax.lax.scan(body, state,
                                (sel, jnp.arange(sel.shape[0])))

        self._epoch_runners[cache_key] = run
        return run

    def fit(self, state: TrainState, loader: GeoDataLoader, epochs: int = 1,
            eval_data=None, eval_every: int = 0, log_every: int = 0,
            log_fn: Callable[[str], None] = print,
            measure: Optional[Measure] = None, scan_epochs: bool = False):
        """Run the training loop.

        - ``log_every=N``: record/log loss+train_acc every N iterations;
        - ``eval_every=N``: compute test accuracy every N iterations
          (independent of log_every); 0 = evaluate at each epoch end;
        - records accumulate in ``measure`` (a fresh one by default);
        - ``scan_epochs=True`` (requires a device-cached loader) runs each
          epoch as one scanned device program: per-iteration logging
          coarsens to per-epoch (mean loss/acc over the epoch), eval still
          runs between epochs.

        Pipelined sync (``GEOMX_PIPELINE_DEPTH=1``): the first step from
        a fresh state is the warmup bubble (a zero aggregate applies
        while the pipeline fills) and one aggregate stays in flight when
        fit returns — call ``drain_pipeline`` after the final fit to land
        it.  Both the bubble and the in-flight buffer live in
        ``sync_state``, so a checkpointed run resumes mid-pipeline with
        no re-warmup.

        Returns (state, list of record dicts).
        """
        measure = measure if measure is not None else Measure()
        measure.reset_clock()
        # iteration numbering restarts per fit, so the telemetry delta
        # base must too — a stale high-water mark from a previous fit
        # would silently swallow this fit's step/byte counter increments
        self._telem_last_it = 0
        # step-time attribution windows restart per fit too: mark the
        # trace clock now so a long-lived process whose global profiler
        # accumulated spans across earlier fits (or other profiled work)
        # attributes only THIS fit's steps — both for the fit-end
        # geomx_phase_fraction summary and the per-publish flight windows
        from geomx_tpu.utils.profiler import get_profiler
        prof = get_profiler()
        fit_since_us = prof.now_us() if prof.running else None
        self._attr_window_us = fit_since_us
        if scan_epochs:
            if not getattr(loader, "device_cache", False):
                raise ValueError("scan_epochs requires device_cache=True "
                                 "on the loader")
            run = self._epoch_runner(loader)
            it = 0
            for epoch in range(epochs):
                sel, key = loader.epoch_indices(epoch)
                state, ms = run(state, loader._dev_x, loader._dev_y,
                                sel, key)
                it += loader.steps_per_epoch
                fields = {}
                if log_every:
                    ms = jax.device_get(ms)
                    fields.update(
                        loss=float(np.mean(ms["loss"])),
                        train_acc=float(np.mean(ms["accuracy"])))
                    if self._telemetry and "telemetry" in ms:
                        # scanned epoch: probe values carry a leading
                        # step dimension; publish the last step's
                        self._publish_telemetry(ms["telemetry"], it,
                                                stacked=True)
                elif self._telemetry:
                    # log_every=0: still publish the epoch's last step
                    # (same fallback the non-scanned loop has)
                    ms = jax.device_get(ms)
                    if "telemetry" in ms:
                        self._publish_telemetry(ms["telemetry"], it,
                                                stacked=True)
                if eval_data is not None:
                    fields["test_acc"] = self.evaluate(state, *eval_data)
                if fields:
                    rec = measure.add(epoch=epoch, iteration=it, **fields)
                    log_fn(json.dumps(rec))
            jax.block_until_ready(state.step)
            self._capsule_checkpoint(prof)
            return state, measure.records
        # Virtual CPU meshes deadlock XLA's collective rendezvous with more
        # than a few in-flight async programs, so there we consume metrics
        # every step.  On a real accelerator that blocking device_get would
        # serialize host work into the step time and cap MFU; instead let
        # XLA's async dispatch run ahead and only sync on log/eval
        # boundaries (bounded every `sync_every` steps as a backstop).
        on_cpu = jax.devices()[0].platform == "cpu"
        sync_every = 1 if on_cpu else max(1, log_every or 32)
        it = 0
        # step-time attribution (telemetry/attribution.py): when the
        # host profiler is running, every step dispatch is bracketed as
        # a train/step + train/compute span pair so attribute_trace can
        # partition the fit's wall clock into compute / comms / stall.
        # scope() no-ops when the profiler is off.  Caveat: with async
        # dispatch the compute span measures dispatch+host time only —
        # the CPU backend (and any blocking sync_every boundary) is the
        # regime where it is the real step.
        for epoch in range(epochs):
            for xb, yb in loader.epoch(epoch, prefetch=self._prefetch):
                # arm the auditor on the first batch (abstract trace of
                # the active program; no-op unless GEOMX_AUDIT is on)
                self._audit_capture(state, xb, yb)
                if self._telemetry and not self._memory_gauge_published:
                    # once per trainer: the per-chip step-memory gauges
                    # (geomx_step_memory_bytes) from the compiled program
                    self.publish_memory_metrics(state, xb, yb)
                with prof.scope("train/step", "step",
                                args={"step": it}):
                    with prof.scope("train/compute", "compute"):
                        state, metrics = self.train_step(state, xb, yb)
                        it += 1
                        # the log/sync boundary wait is device compute
                        # (on the CPU backend the whole step; on an
                        # accelerator the async-dispatch catch-up), so
                        # it stays inside the compute span — attributed
                        # host_stall is then genuinely the input
                        # pipeline and dispatch gaps, which is what the
                        # GEOMX_PREFETCH acceptance (bench.py
                        # --compare-mfu) measures
                        synced = None
                        if log_every and it % log_every == 0:
                            synced = jax.device_get(metrics)
                        elif it % sync_every == 0:
                            jax.block_until_ready(metrics["loss"])
                fields = {}
                if synced is not None:
                    metrics = synced
                    fields.update(loss=float(metrics["loss"]),
                                  train_acc=float(metrics["accuracy"]))
                    if self._telemetry and "telemetry" in metrics:
                        self._publish_telemetry(metrics["telemetry"], it)
                if eval_data is not None and eval_every and it % eval_every == 0:
                    fields["test_acc"] = self.evaluate(state, *eval_data)
                if fields:
                    rec = measure.add(epoch=epoch, iteration=it, **fields)
                    log_fn(json.dumps(rec))
            if self._telemetry and not log_every and it:
                # no log boundary ever synced this epoch: publish the
                # epoch's last step so the registry/event log still track
                # a log_every=0 run (one device_get per epoch)
                last = jax.device_get(metrics)
                if "telemetry" in last:
                    self._publish_telemetry(last["telemetry"], it)
            if eval_data is not None and not eval_every:
                rec = measure.add(epoch=epoch, iteration=it,
                                  test_acc=self.evaluate(state, *eval_data))
                log_fn(json.dumps(rec))
        if self._telemetry and prof.running:
            # publish the fit's phase-fraction summary from the step
            # spans recorded above (geomx_phase_fraction gauges) — the
            # scrapeable form of bench --attribute's breakdown
            from geomx_tpu.telemetry.attribution import (
                attribute_trace, publish_attribution)
            att = attribute_trace(prof.to_doc(), since_us=fit_since_us)
            if att["num_steps"]:
                publish_attribution(att["summary"])
        self._capsule_checkpoint(prof)
        return state, measure.records

    def _capsule_checkpoint(self, prof) -> None:
        """Refresh the run capsule at a fit boundary: attach the
        latest profiler trace (replacing this rank's previous one) and
        rewrite the archive atomically.  A crash between fits leaves
        the previous complete capsule."""
        if self._capsule is None:
            return
        if prof.running:
            # Profiler() defaults self.rank = None — the getattr
            # fallback alone never applies, hence the `or 0`
            rank = getattr(prof, "rank", None)
            self._capsule.add_trace(prof.to_doc(),
                                    label=f"rank{rank if rank is not None else 0}")
        self._capsule.write()

    def close_capsule(self) -> None:
        """Deterministically finish capsule recording: stop the
        sampler, detach the observatory tap and write the final
        archive.  (A garbage-collected trainer stops its sampler/tap
        via finalizers, but does not write.)"""
        if self._capsule is not None:
            self._capsule.close()
