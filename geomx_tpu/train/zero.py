"""ZeRO-sharded bucketed weight update (``GEOMX_ZERO=1``).

Every sync algorithm except MultiGPS's big-leaf path ends the step with a
fully *replicated* weight update: each chip holds the whole optimizer
state and redundantly applies the identical update W times per party, so
per-chip optimizer memory and update compute do not shrink as the worker
axis grows.  "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (PAPERS.md) shows the decomposition

    allreduce(g); update(all)   ==   reduce_scatter(g);
                                     update(my 1/W shard);
                                     all_gather(params)

is free in summed wire bytes and wins both memory (optimizer + error-
feedback state drop ~1/W per chip) and update time (each chip updates
1/W of the weights).  This module applies that decomposition to the
*bucketed flat-gradient engine* (compression/bucketing.py): the unit of
sharding is the fused fp32 bucket, so each worker owns one contiguous,
lane-aligned ``1/W`` slice of every bucket —

- worker tier (ICI): ``psum_scatter`` on the flat buckets replaces the
  worker-axis allreduce; each chip keeps the party-mean of its shard;
- dc tier (DCN): the configured compressor runs per *shard* — each chip
  compresses, transfers and decompresses only its slice, so the sparse
  path never materializes a bucket-dense per-party intermediate
  (Ok-Topk, "Near-Optimal Sparse Allreduce", PAPERS.md) and EF
  residuals live shard-local;
- update: the optimizer runs on flat bucket shards (state allocated
  shard-shaped — the ~1/W per-chip memory claim);
- one ``all_gather`` per bucket rebuilds the replicated params for the
  next forward.

Semantics note: element-wise optimizers (SGD/momentum/Adam/...) are
numerically identical to the replicated update; optimizers coupling
across a whole tensor (global-norm clipping) would see per-shard
statistics — the same caveat MultiGPS documents.

In the replica-axes state scheme (train/state.py) a shard leaf is
``[num_parties, workers_per_party, shard_len]`` sharded ``P(dc,
worker)``: slot ``(p, w)`` physically holds only worker ``w``'s shard,
so the content *differs across the worker axis by design* — checkpoint
and catch-up paths must gather all W shards, not copy ``(0, 0)``
(``Trainer.save_checkpoint`` / ``load_checkpoint`` handle this,
including re-sharding onto a different worker count).
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from geomx_tpu.compression.bucketing import _LANE_PAD, BucketedCompressor


class ZeroPlan:
    """The sharded-update plan over the worker (ICI) axis.

    Built by ``train.step.build_train_step`` when ``config.zero`` is
    set and bound into the sync algorithm (``SyncAlgorithm.bind_zero``).
    Holds only static layout facts — W and the lane alignment — plus
    the in-``shard_map`` shard ops; the bucket layout itself stays
    owned by the :class:`BucketedCompressor` so the ZeRO path slices
    the exact coordinates the replicated path fuses.
    """

    def __init__(self, workers_per_party: int, lane: int = _LANE_PAD):
        if workers_per_party < 1:
            raise ValueError("workers_per_party must be >= 1")
        self.W = int(workers_per_party)
        self.lane = int(lane)
        self.bucketed: "BucketedCompressor | None" = None  # bind_compressor
        # set by build_train_step under GEOMX_FUSED_OPTIM: the static
        # spec routes apply_shard_update through the fused Pallas
        # kernels (ops/optim_pallas.py) over the same bucket shards
        self.fused_spec = None
        self.fused_interpret = False

    @property
    def pad_to(self) -> int:
        """Bucket padding that makes every shard lane-aligned: each of
        the W contiguous shards is a multiple of the TPU lane width (and
        of the 2-bit packer's 16-codes word)."""
        return self.lane * self.W

    # ---- wiring ------------------------------------------------------------

    def bind_compressor(self, dc_compressor) -> BucketedCompressor:
        """Validate the dc-tier compressor stack for the ZeRO path and
        re-align its bucket padding so buckets split into W lane-aligned
        shards.  Returns the underlying :class:`BucketedCompressor`.
        Must run before the first trace resolves a bucket layout."""
        from geomx_tpu.sync.pipeline import PipelinedCompressor
        comp = dc_compressor
        if isinstance(comp, PipelinedCompressor):
            comp = comp.inner
        if not isinstance(comp, BucketedCompressor):
            raise ValueError(
                "GEOMX_ZERO requires the bucketed dc-tier engine: the "
                "shard unit is the fused flat bucket.  Re-enable "
                "bucketing (GEOMX_BUCKET_BYTES > 0) and use a dc "
                f"compressor it can wrap (got "
                f"{getattr(dc_compressor, 'name', type(dc_compressor).__name__)!r})")
        if comp.pad_to % self.pad_to:
            comp.pad_to = self.pad_to
            comp._bucketers.clear()  # layouts cached under the old pad
        self.bucketed = comp
        return comp

    # ---- inside shard_map --------------------------------------------------

    def shard_len(self, bucket_size: int) -> int:
        return bucket_size // self.W

    def scatter_bucket(self, bucket: jax.Array,
                       axis_name: str) -> jax.Array:
        """Worker-tier mean reduce of one flat bucket: psum_scatter, each
        slot keeps its contiguous lane-aligned 1/W shard."""
        if self.W == 1:
            return bucket
        s = self.shard_len(bucket.size)
        return lax.psum_scatter(bucket.reshape(self.W, s), axis_name,
                                scatter_dimension=0) / self.W

    def slice_shard(self, bucket: jax.Array, widx: jax.Array) -> jax.Array:
        """This worker's shard of a *replicated* flat bucket (params,
        stale copies) — a slice, no collective."""
        if self.W == 1:
            return bucket
        s = self.shard_len(bucket.size)
        return lax.dynamic_slice(bucket, (widx * s,), (s,))

    def gather_bucket(self, shard: jax.Array, axis_name: str) -> jax.Array:
        """Rebuild the full flat bucket from the W worker shards."""
        if self.W == 1:
            return shard
        return lax.all_gather(shard, axis_name).reshape(-1)

    def tree_shards(self, tree: Any, bk, widx: jax.Array) -> List[jax.Array]:
        """Flatten a replicated tree onto the bucket layout and slice
        this worker's shard of every bucket (the param/stale-copy side
        of the sharded update)."""
        leaves = jax.tree.leaves(tree)
        return [self.slice_shard(b, widx) for b in bk.flatten(leaves)]

    def apply_shard_update(self, tx, shard_g: List[jax.Array], params: Any,
                           opt_state: Any, axis_name: str) -> tuple:
        """Shard-local optimizer step + param rebuild: slice this
        worker's param shards, run ``tx`` on (shard gradient, shard
        param) pairs, all_gather the updated shards back into full
        buckets and unflatten.  The ONE shard-update path the train
        step (``_zero_sync_update``) and the pipeline drain share —
        they must stay in lockstep or a drained resume silently
        diverges from the in-step update.  Returns
        ``(params, opt_state)``."""
        import optax
        flat_p, treedef = jax.tree.flatten(params)
        bk = self.bucketed.zero_bucketer(flat_p)
        widx = lax.axis_index(axis_name)
        p_shards = [self.slice_shard(b, widx) for b in bk.flatten(flat_p)]
        if self.fused_spec is not None:
            # fused apply (ops/optim_pallas.py): the kernels are shape-
            # agnostic over flat fp32 vectors, so the 1/W bucket shards
            # go through unchanged — the shard-local update and the
            # replicated one share one kernel
            from geomx_tpu.ops.optim_pallas import fused_apply
            new_shards, opt_state = fused_apply(
                self.fused_spec, p_shards, shard_g, opt_state,
                interpret=self.fused_interpret)
        else:
            updates, opt_state = tx.update(shard_g, opt_state, p_shards)
            new_shards = optax.apply_updates(p_shards, updates)
        full = [self.gather_bucket(sh, axis_name) for sh in new_shards]
        return treedef.unflatten(bk.unflatten(full)), opt_state

    # ---- host-side layout --------------------------------------------------

    def shard_example(self, params: Any,
                      bucketed: BucketedCompressor) -> List[jax.Array]:
        """Zero-filled flat bucket shards matching the sharded update's
        operand structure — what ``tx.init`` sees so optimizer state is
        allocated shard-shaped (the ~1/W per-chip memory saving)."""
        leaves = jax.tree.leaves(params)
        bk = bucketed.zero_bucketer(leaves)
        return [jnp.zeros((self.shard_len(n),), jnp.float32)
                for n in bk.bucket_sizes]

    def wire_accounting(self, params: Any) -> dict:
        """Static per-chip wire bytes of the ZeRO step (floats, resolved
        at build time; the scatter-family convention analysis/passes.py's
        ``collective_wire_bytes`` audits): psum_scatter sends
        ``(W-1)/W`` of each bucket, the params all_gather sends this
        chip's shard to W-1 peers, and the dc tier carries the inner
        compressor's payload for one shard."""
        bucketed = self.bucketed
        leaves = jax.tree.leaves(params)
        if not leaves or bucketed is None:
            return {}
        bk = bucketed.zero_bucketer(leaves)
        padded = float(sum(bk.bucket_sizes))
        frac = (self.W - 1) / self.W
        return {
            "zero_scatter_bytes": 4.0 * padded * frac,
            "zero_gather_bytes": 4.0 * padded * frac,
            "dc_wire_bytes": float(
                bucketed.shard_wire_bytes(params, self.W)),
        }


# ---------------------------------------------------------------------------
# checkpoint canonicalization / re-sharding (Trainer.save/load_checkpoint)
# ---------------------------------------------------------------------------

def zero_checkpoint_meta(plan: "ZeroPlan | None", topology) -> dict:
    """The checkpoint meta block that makes sharded state restorable:
    whether the state is ZeRO-sharded and the worker count it was
    sharded over (``load_checkpoint`` re-shards when they differ and
    rejects a GEOMX_ZERO mismatch loudly)."""
    return {
        "zero": plan is not None,
        "num_parties": int(topology.num_parties),
        "workers_per_party": int(topology.workers_per_party),
    }


def _fit_flat(flat: np.ndarray, n_new: int) -> np.ndarray:
    """Truncate/zero-extend a full padded flat bucket to a new padded
    length.  Safe in both directions: positions past the bucket's true
    fill are lane padding, which is zero by construction in every shard
    buffer (grads, EF residuals, optimizer moments of a zero-gradient
    coordinate)."""
    flat = np.asarray(flat).reshape(-1)
    if flat.size >= n_new:
        return np.ascontiguousarray(flat[:n_new])
    return np.concatenate(
        [flat, np.zeros((n_new - flat.size,), flat.dtype)])


def _fit_shard_leaf(old: np.ndarray, t_shape) -> np.ndarray:
    """One ZeRO shard leaf ``[P_old, W_old, ...]`` -> ``[P, W, ...]``:
    concatenate party 0's worker shards back into the full padded flat
    bucket, re-fit it to the new layout's padded length, split into the
    new worker count, and broadcast over parties (shard content is
    identical across parties, distinct across workers)."""
    old = np.asarray(old)
    if old.ndim == 2:  # per-slot scalar (e.g. optax count): replicated
        return np.broadcast_to(old[0, 0], t_shape).copy()
    full = old[0].reshape(-1)  # W_old shards, contiguous == full bucket
    n_new = 1
    for d in t_shape[1:]:
        n_new *= d
    return np.broadcast_to(
        _fit_flat(full, n_new).reshape(t_shape[1:])[None],
        t_shape).copy()


def _fit_replicated_leaf(old: np.ndarray, t_shape) -> np.ndarray:
    """A replicated leaf ``[P_old, W_old, *r]`` -> ``[P, W, *r]``: every
    slot holds the same content, so copy ``(0, 0)`` and broadcast."""
    old = np.asarray(old)
    v = old[0, 0] if old.ndim >= 2 else old
    if v.shape != tuple(t_shape[2:]):
        raise ValueError(
            f"replicated checkpoint leaf {old.shape} does not fit the "
            f"target slot {tuple(t_shape)} — the checkpoint was saved "
            "from a different model/optimizer configuration")
    return np.broadcast_to(v[None, None], t_shape).copy()


def _under_dc_comp(path) -> bool:
    """Shard-bearing sync state is recognized by ITS DICT KEY: the
    ZeRO contract (``SyncAlgorithm.supports_zero``) requires shard-
    shaped dc-tier compressor state to live under the ``"dc_comp"``
    key of ``sync_state`` — FSA, MixedSync and PipelinedSync all do.
    host_zero_state / place_zero_state / reshard_zero_state all route
    on this predicate, so an algorithm that parks shard state under
    any other key would be silently treated as replicated (worker 0's
    slice broadcast over the axis).  Keep the key, or extend this
    predicate together with a bind-time check."""
    from jax.tree_util import DictKey
    return any(isinstance(k, DictKey) and k.key == "dc_comp"
               for k in path)


def host_zero_state(state):
    """One host-side copy of a ZeRO ``TrainState`` for catch-up /
    inspection: replicated fields collapse to copy ``(0, 0)`` exactly
    like ``unreplicate_tree``, but shard-bearing fields (the optimizer
    state and every ``dc_comp`` subtree) keep party 0's FULL worker axis
    — copying ``(0, 0)`` there would silently drop workers 1..W-1's
    shards."""
    from jax.tree_util import tree_map_with_path

    from geomx_tpu.train.state import TrainState

    def rep(x):
        return np.asarray(jax.device_get(x))[0, 0]

    def shard(x):
        return np.asarray(jax.device_get(x))[0]

    return TrainState(
        step=np.asarray(jax.device_get(state.step)),
        params=jax.tree.map(rep, state.params),
        opt_state=jax.tree.map(shard, state.opt_state),
        model_state=jax.tree.map(rep, state.model_state),
        sync_state=tree_map_with_path(
            lambda p, x: shard(x) if _under_dc_comp(p) else rep(x),
            state.sync_state))


def place_zero_state(host_state, topology, mesh):
    """Inverse of :func:`host_zero_state`: re-place a host ZeRO state on
    the mesh — replicated fields broadcast over both replica axes,
    shard-bearing fields (leading ``[W, ...]``) broadcast over parties
    only, so every worker slot gets back exactly its own shard."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.tree_util import tree_map_with_path

    from geomx_tpu.topology import DC_AXIS, WORKER_AXIS
    from geomx_tpu.train.state import TrainState, replicate_tree

    sharding = NamedSharding(mesh, P(DC_AXIS, WORKER_AXIS))

    def shard(x):
        x = np.asarray(x)
        if x.shape[0] != topology.workers_per_party:
            raise ValueError(
                f"sharded state leaf carries {x.shape[0]} worker shards "
                f"but this topology has {topology.workers_per_party} "
                "workers per party — re-shard the checkpoint "
                "(Trainer.load_checkpoint) instead of installing it "
                "directly")
        return jax.device_put(
            np.broadcast_to(x[None], (topology.num_parties,) + x.shape),
            sharding)

    return TrainState(
        step=jax.device_put(jnp.asarray(host_state.step),
                            NamedSharding(mesh, P())),
        params=replicate_tree(host_state.params, topology, mesh),
        opt_state=jax.tree.map(shard, host_state.opt_state),
        model_state=replicate_tree(host_state.model_state, topology,
                                   mesh),
        sync_state=tree_map_with_path(
            lambda p, x: shard(x) if _under_dc_comp(p)
            else replicate_tree(x, topology, mesh),
            host_state.sync_state))


def reshard_zero_state(host_state, template, mesh):
    """Re-shard a host-side ZeRO ``TrainState`` (numpy leaves with
    ``[P_old, W_old, ...]`` replica axes, as a checkpoint stores them)
    onto ``template``'s topology/shardings.

    Field semantics:

    - ``params`` / ``model_state``: replicated — copy ``(0, 0)``;
    - ``opt_state``: every array leaf is a flat bucket shard (or a
      per-slot scalar) — gather the old worker shards into the full
      padded bucket and re-split for the new worker count;
    - ``sync_state``: leaves under any ``"dc_comp"`` key (EF residuals,
      the pipelined in-flight buffers) are shard-shaped and re-split
      like the optimizer's; everything else (worker-tier state, stale
      copies, the model-state double-buffer) is replicated.

    Shapes come pairwise from ``template`` (same config, new topology),
    so no bucket-identity bookkeeping is needed; a structure mismatch
    surfaces as a clear error instead of silent corruption.
    """
    from jax.tree_util import tree_map_with_path

    from geomx_tpu.train.state import TrainState

    def place(host, like):
        return jax.device_put(host, like.sharding)

    def conv_rep(t, o):
        return place(_fit_replicated_leaf(o, t.shape), t)

    def conv_shard(t, o):
        return place(_fit_shard_leaf(o, t.shape), t)

    def conv_sync(path, t, o):
        return (conv_shard(t, o) if _under_dc_comp(path)
                else conv_rep(t, o))

    try:
        return TrainState(
            step=place(np.asarray(host_state.step), template.step),
            params=jax.tree.map(conv_rep, template.params,
                                host_state.params),
            opt_state=jax.tree.map(conv_shard, template.opt_state,
                                   host_state.opt_state),
            model_state=jax.tree.map(conv_rep, template.model_state,
                                     host_state.model_state),
            sync_state=tree_map_with_path(conv_sync, template.sync_state,
                                          host_state.sync_state))
    except ValueError as e:
        raise ValueError(
            "cannot re-shard checkpoint onto this trainer: the state "
            "trees disagree beyond the worker count (different model, "
            f"optimizer, or sync configuration?) — {e}") from e
