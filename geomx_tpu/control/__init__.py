"""Graft Pilot: the closed-loop WAN controller (docs/control.md).

TSEngine reborn on the telemetry plane (ROADMAP item 3): a
sensor -> policy -> actuator loop that retunes compression ratio,
pipeline depth, and relay topology from LIVE measurements instead of
static env config.

- :mod:`sensors`   — fold links/attribution/probe-registry/resilience
  into one normalized :class:`ControlObservation`;
- :mod:`policy`    — deterministic, hysteresis-guarded policies
  (:class:`RatioPolicy`, :class:`DepthPolicy`, :class:`RelayPolicy`)
  under the :class:`GraftPilot` loop;
- :mod:`actuators` — safe application: ratio changes ride a traced
  scalar operand (no recompile), depth/relay changes go through the
  ``Trainer.apply_control`` recompile boundary, every actuation lands
  in the bounded :class:`DecisionLog` the scheduler serves at
  ``GET /control``.

Gated by ``GEOMX_CONTROL``; the disabled step jaxpr is byte-identical
to a controller-excised build.  Acceptance: ``bench.py
--compare-control`` (a seeded chaos WAN-degradation replay the
controller must beat every static config on).
"""

from geomx_tpu.control.actuators import (CONTROL_KEY, ControlActuator,
                                         DecisionLog, control_enabled,
                                         control_operands,
                                         current_ratio_scale,
                                         get_decision_log,
                                         init_control_operands,
                                         reset_decision_log)
from geomx_tpu.control.policy import (Decision, DepthPolicy, GraftPilot,
                                      RatioPolicy, RelayPolicy)
from geomx_tpu.control.sensors import ControlObservation, ControlSensors

__all__ = [
    "CONTROL_KEY", "ControlActuator", "DecisionLog", "control_enabled",
    "control_operands", "current_ratio_scale", "get_decision_log",
    "init_control_operands", "reset_decision_log",
    "Decision", "DepthPolicy", "GraftPilot", "RatioPolicy", "RelayPolicy",
    "ControlObservation", "ControlSensors",
]
