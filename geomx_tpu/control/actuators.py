"""Actuators: apply Graft Pilot decisions safely (docs/control.md).

Three actuation boundaries, by cost:

- **ratio** — the bsc top-k ratio retunes by rewriting a TRACED SCALAR
  OPERAND (``bsc_ratio_scale``) living in ``sync_state["control"]``.
  The compiled step never changes: the configured ratio is the wire
  CAPACITY (static shapes), the scale picks the effective selection
  count below it, and unemitted slots ride the wire as sentinels the
  decompressor already drops.  ``Trainer.apply_control`` swaps the
  operand host-side with a matching sharding, so the jit cache stays at
  one entry (pinned by ``bench.py --compare-control``).
- **depth / relay** — pipeline-depth switching is a RECOMPILE boundary
  modeled on ``Trainer.apply_membership`` (per-decision cached step
  programs, error-feedback state carried across the swap, the
  collective-signature audit re-verified before the new program is
  installed); relay re-forming is host-plane only (the scheduler's
  relay chain re-forms from the ``LinkObservatory`` snapshot) and
  touches no device program.

Every actuation lands in the process-global :class:`DecisionLog`
(served by the scheduler's ``GET /control``), the telemetry event log,
and — when a :class:`~geomx_tpu.telemetry.flight.FlightRecorder` is
armed — the flight ring's decision sibling, so anomaly bundles show
the last N actuations alongside the step records.

The trace-time plumbing mirrors ``telemetry.probes``' inline sink: the
traced step opens :func:`control_operands` around its sync calls only
when ``GEOMX_CONTROL`` is on, and :func:`current_ratio_scale` returns
``None`` otherwise — so the disabled step jaxpr is byte-identical to a
controller-excised build (the same hard guarantee the telemetry plane
makes, pinned by ``tests/test_control.py``).
"""

from __future__ import annotations

import collections
import contextlib
import threading
from typing import Any, Dict, List, Optional

CONTROL_KEY = "control"


def control_enabled(config: Optional[Any] = None) -> bool:
    """The master control gate: ``config.control`` or ``GEOMX_CONTROL``
    (same numeric-boolean parse as every GEOMX_* knob).  Static —
    evaluated when the step program is built."""
    if config is not None and getattr(config, "control", False):
        return True
    from geomx_tpu.config import _env_bool
    return _env_bool(["GEOMX_CONTROL"], False)


def init_control_operands():
    """The control-operand subtree ``Trainer.init_state`` threads into
    ``sync_state[CONTROL_KEY]``: the bsc ratio scale starts at 1.0 (the
    configured capacity ratio)."""
    import jax.numpy as jnp
    return {"bsc_ratio_scale": jnp.ones((), jnp.float32)}


# ---------------------------------------------------------------------------
# trace-time operand context (the probes' inline-sink pattern)
# ---------------------------------------------------------------------------

_ctl = threading.local()


@contextlib.contextmanager
def control_operands(ops: Dict[str, Any]):
    """Open the traced control operands for the sync stack: compressors
    deep inside the dc tier read them via :func:`current_ratio_scale`
    without threading a parameter through every signature."""
    prev = getattr(_ctl, "ops", None)
    _ctl.ops = ops
    try:
        yield ops
    finally:
        _ctl.ops = prev


def current_ratio_scale():
    """The traced ``bsc_ratio_scale`` operand, or ``None`` when no
    control context is open (the disabled path — zero ops enter the
    jaxpr)."""
    ops = getattr(_ctl, "ops", None)
    if ops is None:
        return None
    return ops.get("bsc_ratio_scale")


# ---------------------------------------------------------------------------
# decision log (bounded, process-global; the scheduler serves it)
# ---------------------------------------------------------------------------

class DecisionLog:
    """Thread-safe bounded history of applied decisions.  Entries are
    plain JSON-able dicts with NO wall-clock fields — two runs of the
    same seeded scenario must produce byte-identical logs (the
    ``bench.py --compare-control`` determinism gate)."""

    def __init__(self, capacity: int = 256):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0 (got {capacity!r})")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity)
        self.total = 0

    def append(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            self._entries.append(dict(entry))
            self.total += 1

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._entries]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.total = 0


_global_log: Optional[DecisionLog] = None
_global_log_lock = threading.Lock()


def get_decision_log() -> DecisionLog:
    global _global_log
    with _global_log_lock:
        if _global_log is None:
            _global_log = DecisionLog()
        return _global_log


def reset_decision_log() -> DecisionLog:
    """Fresh global decision log (test / bench-run isolation)."""
    global _global_log
    with _global_log_lock:
        _global_log = DecisionLog()
        return _global_log


# ---------------------------------------------------------------------------
# the actuator
# ---------------------------------------------------------------------------

class ControlActuator:
    """Routes decisions to their actuation boundary and records every
    application.

    ``trainer``: the :class:`~geomx_tpu.train.trainer.Trainer` whose
    ``apply_control`` owns the ratio/depth boundaries.  ``relay_apply``:
    optional callable receiving the new relay order (host plane — the
    in-process transports or a WAN model install it; the scheduler's
    decision history records it either way).  ``flight``: optional
    FlightRecorder whose decision ring mirrors the log.
    """

    def __init__(self, trainer=None, relay_apply=None, flight=None,
                 log: Optional[DecisionLog] = None,
                 event_log=None):
        self.trainer = trainer
        self.relay_apply = relay_apply
        self.flight = flight if flight is not None else \
            getattr(trainer, "_flight", None)
        self.log = log if log is not None else get_decision_log()
        self._event_log = event_log

    def apply(self, state, decision):
        """Apply one decision; returns the (possibly new) TrainState.
        Unknown kinds raise — a controller emitting a decision no
        actuator understands is a bug, not a log line."""
        kind = getattr(decision, "kind", None)
        if kind in ("ratio", "depth"):
            if self.trainer is None:
                raise ValueError(
                    f"{kind!r} decision needs a trainer-bound actuator "
                    "(ControlActuator(trainer=...))")
            state = self.trainer.apply_control(state, decision)
        elif kind == "relay":
            if self.relay_apply is not None:
                self.relay_apply(list(decision.value))
        else:
            raise ValueError(f"unknown decision kind {kind!r}; "
                             "expected ratio | depth | relay")
        self._record(decision)
        return state

    def _record(self, decision) -> None:
        entry = decision.to_json()
        self.log.append(entry)
        if self.flight is not None:
            self.flight.record_decision(entry)
        from geomx_tpu.telemetry import get_registry, log_event
        reg = get_registry()
        reg.counter("geomx_control_decisions_total",
                    "Controller actuations applied",
                    ("kind",)).labels(kind=entry["kind"]).inc()
        if entry["kind"] == "ratio":
            reg.gauge("geomx_control_ratio",
                      "Current controller-set bsc ratio").set(
                float(entry["value"]))
        elif entry["kind"] == "depth":
            reg.gauge("geomx_control_pipeline_depth",
                      "Current controller-set pipeline depth").set(
                float(entry["value"]))
        # the event kind is positional; the decision's own "kind" field
        # rides as decision_kind so the two never collide
        ev = {("decision_kind" if k == "kind" else k): v
              for k, v in entry.items()}
        if self._event_log is not None:
            self._event_log.emit("control_decision", **ev)
        else:
            log_event("control_decision", **ev)
