"""Deterministic control policies: the Graft Pilot's decision brain.

TSEngine (PAPER.md §6) chose its overlay once per round from measured
throughput; the Graft Pilot generalizes that into four hysteresis-
guarded feedback policies over the telemetry plane's sensors
(:mod:`~geomx_tpu.control.sensors`):

- :class:`RatioPolicy` — per-link compression-ratio retuning.  The
  optimal top-k ratio is a function of the measured bandwidth/compute
  ratio, not a constant ("Evaluation and Optimization of Gradient
  Compression", PAPERS.md): the policy computes the throughput-matched
  operating point (the largest payload the measured bottleneck link
  moves inside one step of compute, with ``headroom``), moves the
  current ratio toward it by a BOUNDED multiplicative step, and never
  lowers it while the error-feedback residual marks the gradient as
  accuracy-unsafe (EF mass comparable to the gradient itself means the
  compressor is already starving the update).
- :class:`DepthPolicy` — pipeline-depth switching: enable
  ``PipelinedSync`` depth-1 when the measured exposed-comms fraction
  crosses the hidden-by-compute threshold, disable when compute
  re-dominates.  Dual thresholds (enter ≫ exit) plus a confirmation
  streak make the switch a Schmitt trigger, not a comparator.
- :class:`RelayPolicy` — relay re-forming: recompute the relay chain
  from the ``LinkObservatory`` bandwidth snapshot (greedy widest-path —
  the widest measured uplink becomes the chain's sink-adjacent relay,
  exactly the paper's ASK1 pairing), with a minimum-gain margin so
  estimate noise cannot thrash the overlay.
- :class:`SloPolicy` — serving-plane routing + shedding (PR 18,
  docs/serving.md): re-point the replica refresh source at the widest
  measured uplink, and shed inference load (explicit 503s, bounded
  steps, Schmitt-guarded on the request-ledger p99) when the serving
  SLO is breached.

Everything here is a pure function of the observation stream plus
bounded internal counters: the same seeded scenario produces the same
decision sequence, which is what makes the chaos-replay acceptance
(``bench.py --compare-control``) and its bit-identical decision-log
gate possible.  No wall clock, no RNG.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, List, Optional, Tuple

from geomx_tpu.control.sensors import ControlObservation


@dataclasses.dataclass(frozen=True)
class Decision:
    """One actuation the pilot wants applied.

    ``kind``: ``"ratio"`` (value = absolute bsc ratio), ``"depth"``
    (value = 0 or 1), ``"relay"`` (value = party order, widest first)
    or ``"slo"`` (value = ``("shed", fraction)`` / ``("route",
    party)``).  ``prev`` is the value being replaced; ``reason`` is a
    deterministic human-readable justification (no timestamps)."""

    step: int
    kind: str
    value: Any
    prev: Any
    reason: str

    def to_json(self) -> dict:
        val = list(self.value) if isinstance(self.value, tuple) \
            else self.value
        prev = list(self.prev) if isinstance(self.prev, tuple) else self.prev
        return {"step": int(self.step), "kind": self.kind, "value": val,
                "prev": prev, "reason": self.reason}


class Cooldown:
    """Per-knob actuation rate limiter: after a decision fires, the
    knob stays untouchable for ``steps`` steps."""

    def __init__(self, steps: int):
        self.steps = max(0, int(steps))
        self._last: Optional[int] = None

    def ready(self, step: int) -> bool:
        return self._last is None or step - self._last >= self.steps

    def fire(self, step: int) -> None:
        self._last = step


def _bottleneck_bps(obs: ControlObservation, peer: str = "global"
                    ) -> Optional[float]:
    """The narrowest confident measured uplink toward ``peer`` — the
    link that gates a synchronous WAN round."""
    vals = [rec["throughput_bps"] for rec in obs.links.values()
            if rec["peer"] == peer and rec["throughput_bps"] is not None]
    return min(vals) if vals else None


class RatioPolicy:
    """Throughput-matched bsc-ratio retuning with an accuracy floor.

    ``base_ratio`` is the CAPACITY (the configured ratio whose k sizes
    the wire buffers); ``bounds = (lo, hi)`` the absolute operating
    range with ``hi <= base_ratio``.  Per decision the ratio moves at
    most ``step_limit``x and only when the target differs from the
    current ratio by more than ``deadband`` (relative) — the hysteresis
    pair that keeps a noisy bandwidth estimate from oscillating the
    knob.  ``ef_unsafe``: when the EF-residual norm exceeds this
    fraction of the gradient norm, lowering is vetoed (raises stay
    allowed) — telemetry's in-situ accuracy floor.

    The matched-point estimate itself is EWMA-smoothed
    (``target_alpha``) across observations — one noisy bandwidth sample
    moves the target a little, never the knob a lot — and the smoother
    keeps integrating through cooldown, so the policy re-emerges from a
    quiet period aimed at the settled target, not the last spike.
    """

    knob = "ratio"

    def __init__(self, base_ratio: float,
                 bounds: Optional[Tuple[float, float]] = None,
                 cooldown: int = 5, step_limit: float = 4.0,
                 deadband: float = 0.25, ef_unsafe: float = 1.0,
                 headroom: float = 1.0, target_alpha: float = 0.3,
                 wire_bytes_per_ratio: Optional[float] = None):
        if base_ratio <= 0:
            raise ValueError(f"base_ratio must be > 0 (got {base_ratio!r})")
        self.base_ratio = float(base_ratio)
        if bounds is None:
            bounds = (self.base_ratio / 8.0, self.base_ratio)
        lo, hi = float(bounds[0]), float(bounds[1])
        if not 0.0 < lo <= hi:
            raise ValueError(f"ratio bounds must satisfy 0 < lo <= hi "
                             f"(got {bounds!r})")
        if hi > self.base_ratio * (1 + 1e-9):
            raise ValueError(
                f"ratio bound hi={hi} exceeds the configured capacity "
                f"ratio {self.base_ratio}: the traced scale can only "
                "tune DOWN from the static wire size — raise the "
                "configured compression ratio instead")
        self.bounds = (lo, hi)
        self.cooldown = Cooldown(cooldown)
        self.step_limit = max(1.0 + 1e-6, float(step_limit))
        self.deadband = max(0.0, float(deadband))
        self.ef_unsafe = float(ef_unsafe)
        self.headroom = float(headroom)
        if not 0.0 < target_alpha <= 1.0:
            raise ValueError(
                f"target_alpha must be in (0, 1] (got {target_alpha!r})")
        self.target_alpha = float(target_alpha)
        self._target: Optional[float] = None  # EWMA-smoothed matched point
        # bytes one party puts on the WAN per unit of ratio (derived
        # from the dense payload when the sensor reports it)
        self.wire_bytes_per_ratio = wire_bytes_per_ratio
        self.current = min(self.base_ratio, hi)

    def _matched_ratio(self, obs: ControlObservation) -> Optional[float]:
        """The throughput-matched operating point: the ratio whose wire
        payload the measured bottleneck uplink moves in ``headroom``
        steps of compute.  None when a required sensor is missing."""
        bw = _bottleneck_bps(obs)
        if bw is None or not obs.compute_s:
            return None
        bpr = self.wire_bytes_per_ratio
        if bpr is None:
            if not obs.dc_dense_bytes:
                return None
            # bsc wire: 2 (value,index) fp32 pairs per selected element
            # = 2x the dense bytes at ratio 1.0
            bpr = 2.0 * obs.dc_dense_bytes
        if bpr <= 0:
            return None
        return bw * obs.compute_s * self.headroom / bpr

    def decide(self, obs: ControlObservation) -> Optional[Decision]:
        raw = self._matched_ratio(obs)
        if raw is not None:
            # smooth FIRST, gate later: the estimate integrates every
            # observation, including those inside the cooldown window
            a = self.target_alpha
            self._target = raw if self._target is None \
                else a * raw + (1 - a) * self._target
        if not self.cooldown.ready(obs.step):
            return None
        target = self._target
        if target is None:
            # sensor-poor fallback: steer on the exposed-comms fraction
            # alone (still deterministic, still hysteresis-guarded)
            if obs.exposed_comms is None:
                return None
            if obs.exposed_comms > 0.30:
                target = self.current / 2.0
            elif obs.exposed_comms < 0.05:
                target = self.current * 2.0
            else:
                return None
        lo, hi = self.bounds
        # accuracy floor: with EF mass rivaling the gradient, the
        # compressor is starving the update — never lower further
        ef_blocked = (obs.ef_residual_norm is not None
                      and obs.grad_norm is not None and obs.grad_norm > 0
                      and obs.ef_residual_norm
                      > self.ef_unsafe * obs.grad_norm)
        target = min(max(target, lo), hi)
        # bounded step toward the target
        new = min(max(target, self.current / self.step_limit),
                  self.current * self.step_limit)
        new = min(max(new, lo), hi)
        if ef_blocked and new < self.current:
            return None
        if abs(new - self.current) <= self.deadband * self.current:
            return None
        prev = self.current
        self.current = new
        self.cooldown.fire(obs.step)
        direction = "lower" if new < prev else "raise"
        return Decision(
            step=obs.step, kind="ratio", value=new, prev=prev,
            reason=f"{direction} toward throughput-matched ratio "
                   f"{target:.6g} (bounds [{lo:g}, {hi:g}])")


class DepthPolicy:
    """Schmitt-trigger pipeline-depth switching on the WAN fraction.

    The gate signal is ``exposed + hidden`` — the step-time fraction
    spent on the wire whether or not compute currently hides it.  Using
    raw exposure instead would self-oscillate: enabling depth-1 hides
    the comms, the measured exposure collapses to ~0, and a naive
    comparator immediately disables what just started working.  The
    WAN fraction is invariant under the actuation it controls (at
    depth 0 it IS the exposure; at depth 1 it is what the exposure
    would return to), so the trigger is a true Schmitt pair: ``enter``
    (fraction above which depth-1 pays) must exceed ``exit`` (below
    which compute dominates even unhidden), and a reading must persist
    ``confirm`` consecutive observations before the switch — one noisy
    attribution window cannot flip the pipeline."""

    knob = "depth"

    def __init__(self, enter: float = 0.25, exit: float = 0.10,
                 confirm: int = 2, cooldown: int = 5, initial: int = 0):
        if not 0.0 <= exit < enter <= 1.0:
            raise ValueError(
                f"need 0 <= exit < enter <= 1 (got exit={exit}, "
                f"enter={enter}) — equal thresholds are a comparator, "
                "not hysteresis")
        if initial not in (0, 1):
            raise ValueError(f"initial depth must be 0 or 1 "
                             f"(got {initial!r})")
        self.enter = float(enter)
        self.exit = float(exit)
        self.confirm = max(1, int(confirm))
        self.cooldown = Cooldown(cooldown)
        # seed from the system's ACTUAL configured depth (from_config
        # wires cfg.pipeline_depth) — a policy that assumes depth 0
        # while the trainer compiled depth 1 could never emit the exit
        # transition that pays off the staleness
        self.current = int(initial)
        self._streak = 0

    def decide(self, obs: ControlObservation) -> Optional[Decision]:
        if obs.exposed_comms is None:
            return None
        wan = obs.exposed_comms + (obs.hidden_comms or 0.0)
        want = self.current
        if self.current == 0 and wan > self.enter:
            want = 1
        elif self.current == 1 and wan < self.exit:
            want = 0
        if want == self.current:
            self._streak = 0
            return None
        self._streak += 1
        if self._streak < self.confirm or not self.cooldown.ready(obs.step):
            return None
        prev = self.current
        self.current = want
        self._streak = 0
        self.cooldown.fire(obs.step)
        why = (f"wan_fraction {wan:.3f} > enter {self.enter:.3f}"
               if want else
               f"wan_fraction {wan:.3f} < exit {self.exit:.3f}")
        return Decision(step=obs.step, kind="depth", value=want, prev=prev,
                        reason=f"pipeline depth {prev}->{want}: {why}")


class RelayPolicy:
    """Greedy widest-path relay re-forming with a minimum-gain margin.

    The candidate chain is the snapshot's parties ordered widest uplink
    first (the ONE ordering rule ``telemetry.links.relay_order`` also
    gives ``LinkObservatory.best_relay_order`` — policy and observatory
    can never drift); the order's head is the relay SINK the other
    parties merge through.  An empty order ``()`` means direct fan-in
    (no relay — the static default).  The thresholds are a Schmitt
    pair: the chain FORMS only when the widest measured uplink is at
    least ``min_gain``x the narrowest, and RELEASES back to direct
    fan-in only when the asymmetry falls below ``release``
    (< ``min_gain``; default three quarters of the way up the margin) —
    an estimate hovering at the form threshold holds the current
    overlay instead of thrashing it, while a degraded link that
    recovers still does not leave the overlay detouring forever."""

    knob = "relay"

    def __init__(self, min_gain: float = 1.5,
                 release: Optional[float] = None, cooldown: int = 5,
                 min_confidence: float = 0.5, peer: str = "global"):
        self.min_gain = max(1.0, float(min_gain))
        if release is None:
            release = 1.0 + 0.75 * (self.min_gain - 1.0)
        if not 1.0 <= release <= self.min_gain:
            raise ValueError(
                f"release must satisfy 1 <= release <= min_gain "
                f"(got release={release}, min_gain={self.min_gain}) — "
                "release == min_gain is a comparator, not hysteresis")
        self.release = float(release)
        self.cooldown = Cooldown(cooldown)
        self.min_confidence = float(min_confidence)
        self.peer = peer
        self.current: Tuple[str, ...] = ()

    def decide(self, obs: ControlObservation) -> Optional[Decision]:
        from geomx_tpu.telemetry.links import relay_order
        if not self.cooldown.ready(obs.step):
            return None
        links = {rec["party"]: rec for rec in obs.links.values()
                 if rec["peer"] == self.peer
                 and rec["throughput_bps"] is not None
                 and rec["confidence"] >= self.min_confidence}
        if len(links) < 2:
            return None
        order = tuple(relay_order(links.values(), peer=self.peer))
        widest = links[order[0]]["throughput_bps"]
        narrowest = links[order[-1]]["throughput_bps"]
        asym = widest / narrowest if narrowest > 0 else math.inf
        prev = self.current
        if asym < self.min_gain:
            # below the form threshold: hold the current overlay inside
            # the [release, min_gain) band, release under it
            if not prev or asym >= self.release:
                return None
            self.current = ()
            self.cooldown.fire(obs.step)
            return Decision(
                step=obs.step, kind="relay", value=(), prev=prev,
                reason=f"release to direct fan-in (asymmetry "
                       f"{asym:.2f}x < release {self.release:g}x)")
        if order == prev:
            return None
        self.current = order
        self.cooldown.fire(obs.step)
        return Decision(
            step=obs.step, kind="relay", value=order, prev=prev,
            reason=f"widest-path chain via {order[0]} "
                   f"(uplinks {widest:.3g} vs narrowest {narrowest:.3g})")


class SloPolicy:
    """Serving-SLO routing + shedding: the fourth policy family
    (docs/serving.md "SLO policy").

    The observation is the gateway's serving stats (``stats_fn`` — a
    zero-arg callable returning ``{"p99_s", "queue_depth", ...}`` or
    None before traffic) plus the shared ``LinkObservatory`` snapshot
    already on the :class:`ControlObservation`.  Two deterministic
    sub-decisions, both ``kind="slo"``:

    - **shed** (``value=("shed", fraction)``): when the measured
      request p99 exceeds ``target_p99_s`` for ``confirm`` consecutive
      evaluations, the shed fraction rises by a bounded ``shed_step``;
      when p99 falls under the Schmitt exit (``release_p99_s`` <
      target) for ``confirm`` evaluations it steps back down.  Sheds
      are explicit 503s the gateway counts — load the SLO cannot carry
      is refused loudly, never queued into timeout loss;
    - **route** (``value=("route", party)``): the refresh source is
      re-pointed at the widest confident measured uplink from the link
      snapshot — the same one ordering rule the relay policy uses, so
      observatory and both overlay consumers can never disagree.

    Same determinism contract as the other three families: pure
    function of the observation stream + bounded counters; no wall
    clock, no RNG."""

    knob = "slo"

    def __init__(self, stats_fn, target_p99_s: float = 0.5,
                 release_p99_s: Optional[float] = None,
                 shed_step: float = 0.1, shed_max: float = 0.9,
                 confirm: int = 2, cooldown: int = 5,
                 min_confidence: float = 0.5, peer: str = "global"):
        if target_p99_s <= 0:
            raise ValueError(
                f"target_p99_s must be > 0 (got {target_p99_s!r})")
        if release_p99_s is None:
            release_p99_s = 0.5 * target_p99_s
        if not 0.0 < release_p99_s < target_p99_s:
            raise ValueError(
                f"need 0 < release < target (got release={release_p99_s}, "
                f"target={target_p99_s}) — equal thresholds are a "
                "comparator, not hysteresis")
        self.stats_fn = stats_fn
        self.target_p99_s = float(target_p99_s)
        self.release_p99_s = float(release_p99_s)
        self.shed_step = max(1e-6, float(shed_step))
        self.shed_max = min(1.0, max(0.0, float(shed_max)))
        self.confirm = max(1, int(confirm))
        self.cooldown = Cooldown(cooldown)
        self.min_confidence = float(min_confidence)
        self.peer = peer
        self.current = 0.0            # active shed fraction
        self.route: Optional[str] = None   # current refresh source
        self._over_streak = 0
        self._under_streak = 0

    def _route_decision(self, obs: ControlObservation
                        ) -> Optional[Decision]:
        links = {rec["party"]: rec for rec in obs.links.values()
                 if rec["peer"] == self.peer
                 and rec["throughput_bps"] is not None
                 and rec["confidence"] >= self.min_confidence}
        if not links:
            return None
        from geomx_tpu.telemetry.links import relay_order
        order = tuple(relay_order(links.values(), peer=self.peer))
        widest = order[0]
        if widest == self.route:
            return None
        prev = self.route
        self.route = widest
        return Decision(
            step=obs.step, kind="slo", value=("route", widest),
            prev=("route", prev),
            reason=f"refresh source -> widest measured uplink {widest} "
                   f"({links[widest]['throughput_bps']:.3g} B/s)")

    def decide(self, obs: ControlObservation) -> Optional[Decision]:
        # routing re-points freely (no cooldown contention with shed:
        # it only fires when the widest uplink actually changes)
        route = self._route_decision(obs)
        if route is not None:
            return route
        stats = self.stats_fn() if self.stats_fn is not None else None
        p99 = None if not stats else stats.get("p99_s")
        if p99 is None:
            self._over_streak = self._under_streak = 0
            return None
        if p99 > self.target_p99_s:
            self._over_streak += 1
            self._under_streak = 0
        elif p99 < self.release_p99_s:
            self._under_streak += 1
            self._over_streak = 0
        else:
            # inside the hysteresis band: hold
            self._over_streak = self._under_streak = 0
            return None
        want = self.current
        if self._over_streak >= self.confirm \
                and self.current < self.shed_max:
            want = min(self.shed_max, self.current + self.shed_step)
        elif self._under_streak >= self.confirm and self.current > 0.0:
            want = max(0.0, self.current - self.shed_step)
        if want == self.current or not self.cooldown.ready(obs.step):
            return None
        prev = self.current
        self.current = want
        self._over_streak = self._under_streak = 0
        self.cooldown.fire(obs.step)
        direction = "raise" if want > prev else "lower"
        bound = self.target_p99_s if want > prev else self.release_p99_s
        cmp = ">" if want > prev else "<"
        return Decision(
            step=obs.step, kind="slo", value=("shed", want),
            prev=("shed", prev),
            reason=f"{direction} shed to {want:.2f}: request p99 "
                   f"{p99:.4g}s {cmp} {bound:.4g}s "
                   f"for {self.confirm} evaluations")


class GraftPilot:
    """The closed loop: sensors -> policies -> decisions, evaluated
    every ``interval`` steps.  Construction wires defaults from
    :class:`~geomx_tpu.config.GeoConfig` via :meth:`from_config`."""

    def __init__(self, sensors, ratio: Optional[RatioPolicy] = None,
                 depth: Optional[DepthPolicy] = None,
                 relay: Optional[RelayPolicy] = None,
                 slo: Optional[SloPolicy] = None,
                 interval: int = 1):
        self.sensors = sensors
        self.policies = [p for p in (ratio, depth, relay, slo)
                         if p is not None]
        if not self.policies:
            raise ValueError("GraftPilot needs at least one policy")
        self.interval = max(1, int(interval))
        self.decisions_made = 0

    @classmethod
    def from_config(cls, cfg, sensors, base_ratio: float,
                    **overrides) -> "GraftPilot":
        """Policy stack from the GEOMX_CONTROL_* knobs: ratio bounds
        from ``control_ratio_bounds`` ("lo,hi", default
        [base/8, base]), shared cooldown from ``control_cooldown``,
        evaluation interval from ``control_interval``."""
        bounds = None
        raw = getattr(cfg, "control_ratio_bounds", "") or ""
        if raw.strip():
            parts = [float(s) for s in raw.split(",")]
            if len(parts) != 2:
                raise ValueError(
                    f"GEOMX_CONTROL_RATIO_BOUNDS must be 'lo,hi' "
                    f"(got {raw!r})")
            bounds = (parts[0], parts[1])
        cooldown = getattr(cfg, "control_cooldown", 5)
        kw = dict(
            ratio=RatioPolicy(base_ratio, bounds=bounds, cooldown=cooldown),
            depth=DepthPolicy(
                cooldown=cooldown,
                initial=1 if getattr(cfg, "pipeline_depth", 0) else 0),
            relay=RelayPolicy(cooldown=cooldown),
            interval=getattr(cfg, "control_interval", 1))
        kw.update(overrides)
        return cls(sensors, **kw)

    def tick(self, step: int, now: Optional[float] = None
             ) -> List[Decision]:
        """One control evaluation: observe once, let every policy vote.
        Returns the decisions to actuate (possibly empty); no-ops on
        steps that are not a multiple of ``interval``."""
        if step % self.interval:
            return []
        obs = self.sensors.observe(step, now=now)
        out: List[Decision] = []
        for pol in self.policies:
            d = pol.decide(obs)
            if d is not None:
                out.append(d)
        self.decisions_made += len(out)
        return out
