"""Sensors: fold the observability surfaces into one observation.

PR 5/PR 8 built everything a controller needs to *see* — per-link EWMA
throughput/RTT/loss with staleness confidence
(``telemetry/links.LinkObservatory``, built expressly as the controller
sensor interface), the exposed-vs-hidden comms fraction
(``telemetry/attribution`` publishing ``geomx_phase_fraction``),
achieved density / EF-residual norms / wire accounting (the
``geomx_step_probe`` registry family the Trainer publishes), and the
roster epoch + live mask (``resilience/liveness``).  This module is the
adapter: :class:`ControlSensors` reads each surface through its public
API and normalizes the result into one frozen
:class:`ControlObservation` per tick — policies consume ONE shape and
never re-implement staleness filtering, registry label plumbing, or
membership bookkeeping.

Determinism: an observation is a pure read of the surfaces at an
explicit ``now`` (virtual time in replays); nothing here samples a
clock or mutates sensor state.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ControlObservation:
    """One normalized controller input (all fields Optional-safe: a
    missing surface reads as None, and policies degrade gracefully)."""

    step: int
    # per-link quality, already staleness-filtered (links.py snapshot
    # records keyed "party->peer")
    links: Dict[str, dict]
    # step-time phase fractions (attribution.py; sum to ~1 when present)
    exposed_comms: Optional[float] = None
    hidden_comms: Optional[float] = None
    compute_fraction: Optional[float] = None
    host_stall: Optional[float] = None
    # absolute per-step compute seconds when the caller can supply it
    # (bench's WAN model does); fraction-only consumers leave it None
    compute_s: Optional[float] = None
    # in-graph probe registry reads (geomx_step_probe)
    ef_residual_norm: Optional[float] = None
    grad_norm: Optional[float] = None
    achieved_density: Optional[float] = None
    emitted_fraction: Optional[float] = None
    ratio_scale: Optional[float] = None
    dc_wire_bytes: Optional[float] = None
    dc_dense_bytes: Optional[float] = None
    # resilience surface
    roster_epoch: int = 0
    live_mask: Optional[Tuple[bool, ...]] = None
    num_live: Optional[int] = None
    # fleet surface (telemetry/fleetscope.py publishing the
    # geomx_fleet_rollup gauge family): fleet-wide truth so SloPolicy
    # can steer on the whole fleet, not gateway-local numbers
    fleet_qps: Optional[float] = None
    fleet_shed_rate: Optional[float] = None
    fleet_staleness_max_s: Optional[float] = None
    fleet_burn_rate: Optional[float] = None
    fleet_propagation_p99_s: Optional[float] = None
    fleet_nodes_dead: Optional[int] = None


# probe-name -> observation-field mapping for the registry reads
_PROBE_FIELDS = {
    "ef_residual_norm": "ef_residual_norm",
    "grad_norm_global": "grad_norm",
    "dc_nonzero_fraction": "achieved_density",
    "bsc_emitted_fraction": "emitted_fraction",
    "control_ratio_scale": "ratio_scale",
    "dc_wire_bytes": "dc_wire_bytes",
    "dc_dense_bytes": "dc_dense_bytes",
}


def _gauge_values(registry, family: str) -> Dict[str, float]:
    """{first-label-value: gauge value} for one registry family ({}
    when the family was never registered)."""
    fam = registry.get(family)
    if fam is None:
        return {}
    out: Dict[str, float] = {}
    for label_values, child in fam.children():
        key = label_values[0] if label_values else ""
        out[key] = float(child.value)
    return out


class ControlSensors:
    """The controller's one read path over the observability planes.

    ``observatory``: a :class:`~geomx_tpu.telemetry.links.
    LinkObservatory` (default: the process-global one).  ``registry``:
    a :class:`~geomx_tpu.telemetry.registry.MetricRegistry` (default:
    process-global).  ``liveness``: an optional
    :class:`~geomx_tpu.resilience.liveness.PartyLivenessController`.
    ``min_confidence``: the staleness gate applied to link estimates
    (links below it are invisible to every policy).  ``compute_s_fn``:
    optional callable ``step -> seconds`` supplying absolute compute
    time when the host knows it (bench's WAN model; a profiler-derived
    estimate in live runs).  ``registry_fn``: the REPLAY path
    (telemetry/capsule.py) — a callable ``step -> registry-like``
    serving the registry view recorded AT that step, so an offline
    re-tick over a run capsule reads exactly what the live tick read;
    takes precedence over ``registry``.
    """

    def __init__(self, observatory=None, registry=None, liveness=None,
                 min_confidence: float = 0.5, compute_s_fn=None,
                 registry_fn=None):
        self.observatory = observatory
        self.registry = registry
        self.liveness = liveness
        self.min_confidence = float(min_confidence)
        self.compute_s_fn = compute_s_fn
        self.registry_fn = registry_fn

    def _observatory(self):
        if self.observatory is not None:
            return self.observatory
        from geomx_tpu.telemetry.links import get_link_observatory
        return get_link_observatory()

    def _registry(self):
        if self.registry is not None:
            return self.registry
        from geomx_tpu.telemetry.registry import get_registry
        return get_registry()

    def observe(self, step: int,
                now: Optional[float] = None) -> ControlObservation:
        """One normalized observation at ``step`` (pass ``now`` when
        replaying on a virtual clock so staleness decays on replay
        time, not wall time)."""
        links = self._observatory().snapshot(
            now=now, min_confidence=self.min_confidence)
        reg = self.registry_fn(step) if self.registry_fn is not None \
            else self._registry()
        probes = _gauge_values(reg, "geomx_step_probe")
        phases = _gauge_values(reg, "geomx_phase_fraction")
        fields: Dict[str, Optional[float]] = {}
        for probe, field in _PROBE_FIELDS.items():
            if probe in probes:
                fields[field] = float(probes[probe])
        obs = dict(
            step=int(step), links=links,
            exposed_comms=phases.get("exposed_comms"),
            hidden_comms=phases.get("hidden_comms"),
            compute_fraction=phases.get("compute"),
            host_stall=phases.get("host_stall"),
            **fields)
        fleet = _gauge_values(reg, "geomx_fleet_rollup")
        for gkey, field in (("qps", "fleet_qps"),
                            ("shed_rate", "fleet_shed_rate"),
                            ("replica_staleness_max_s",
                             "fleet_staleness_max_s"),
                            ("burn_rate_max", "fleet_burn_rate"),
                            ("propagation_p99_s",
                             "fleet_propagation_p99_s")):
            if gkey in fleet:
                obs[field] = float(fleet[gkey])
        if "nodes_dead" in fleet:
            obs["fleet_nodes_dead"] = int(fleet["nodes_dead"])
        if self.compute_s_fn is not None:
            obs["compute_s"] = float(self.compute_s_fn(step))
        if self.liveness is not None:
            epoch = self.liveness.epoch
            obs["roster_epoch"] = int(epoch.version)
            obs["live_mask"] = tuple(bool(b) for b in epoch.live_mask)
            obs["num_live"] = int(epoch.num_live)
        return ControlObservation(**obs)
