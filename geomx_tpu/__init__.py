"""geomx_tpu — a TPU-native framework for geo-distributed ML training.

A from-scratch JAX/XLA re-design of the capabilities of GeoMX
(https://github.com/INET-RC/GeoMX): hierarchical two-tier parameter-server
training ("HiPS") across data centers, re-expressed as SPMD collectives over a
2-level TPU device mesh — the intra-party tier rides ICI, the cross-party
(geo/WAN) tier rides DCN — plus the reference's WAN-communication accelerators
re-built TPU-first:

- Bi-Sparse top-k gradient sparsification (``compression.bisparse``)
- FP16 low-precision transmission (``compression.fp16``)
- Mixed-Precision Quantization / MPQ (``compression.mpq``)
- 2-bit quantization with error feedback (``compression.twobit``)
- DGT contribution-aware differential transmission (``sync.dgt``)
- P3 priority-based parameter propagation (``transport.p3``)
- TSEngine adaptive communication scheduling (``transport.tsengine``)
- MultiGPS parameter sharding (``parallel.multigps``)

Beyond the reference's scope: long-context sequence parallelism — ring
attention (``parallel.ring_attention``) and Ulysses all-to-all
(``parallel.ulysses``) over a third "sp" mesh axis
(``HiPSTopology(sp_degree=n)``), first-class through the Trainer — and
elastic resilience (``resilience``): versioned party-membership epochs,
degraded-mode WAN sync that renormalizes the dc-tier mean over surviving
parties, re-admission catch-up, and a deterministic seeded chaos harness
(docs/resilience.md); and a unified telemetry plane (``telemetry``):
in-graph gradient-health probes whose disabled path is jaxpr-identical
to a telemetry-free build, a process-global metric registry with
Prometheus export, cross-party WAN round tracing with merged Chrome
timelines, and a bounded JSONL event log (docs/telemetry.md).

Synchronization algorithms: FSA (fully-synchronous, default), MixedSync
(async global tier with optional DCASGD delay compensation), and HFA
(hierarchical frequency aggregation).

Reference layer map and parity inventory: see SURVEY.md at the repo root.
"""

__version__ = "0.1.0"

from geomx_tpu.config import GeoConfig
from geomx_tpu.topology import (DC_AXIS, SP_AXIS, WORKER_AXIS,
                                HiPSTopology)

__all__ = [
    "HiPSTopology",
    "GeoConfig",
    "DC_AXIS",
    "SP_AXIS",
    "WORKER_AXIS",
    "__version__",
]
