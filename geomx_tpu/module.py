"""High-level Module API — the ``mx.mod.Module`` surface.

Reference: python/mxnet/module/ (~4000 LoC): a model + optimizer + kvstore
bound into one object with ``fit / predict / score /
save_checkpoint / load_checkpoint`` and epoch callbacks.  Here it is a
thin veneer over ``Trainer`` (which already owns the jitted SPMD step),
provided for users coming from the reference API; new code should use
``Trainer`` directly.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from geomx_tpu import metric as metric_mod
from geomx_tpu.config import GeoConfig
from geomx_tpu.topology import HiPSTopology
from geomx_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


class Module:
    def __init__(self, model: Union[str, Any],
                 topology: Optional[HiPSTopology] = None,
                 config: Optional[GeoConfig] = None,
                 optimizer: Union[str, Any] = "adam",
                 optimizer_params: Optional[dict] = None,
                 sync: Optional[Any] = None,
                 num_classes: int = 10):
        from geomx_tpu.models import get_model
        from geomx_tpu.optim import get_optimizer
        from geomx_tpu.sync import get_sync_algorithm
        from geomx_tpu.train import Trainer

        self.config = config or GeoConfig.from_env()
        self.topology = topology or HiPSTopology(
            self.config.num_parties, self.config.workers_per_party)
        if isinstance(model, str):
            model = get_model(model, num_classes=num_classes)
        if isinstance(optimizer, str):
            optimizer = get_optimizer(optimizer,
                                      **(optimizer_params or {}))
        if sync is None:
            sync = get_sync_algorithm(self.config)
        self.trainer = Trainer(model, self.topology, optimizer,
                               sync=sync, config=self.config)
        self.state = None

    # ---- binding / params (reference module.bind / get_params) -----------

    def bind(self, sample_input: np.ndarray, rng: Optional[Any] = None):
        """Initialize state from one sample batch (the reference's
        bind+init_params collapse into one call here)."""
        rng = jax.random.PRNGKey(0) if rng is None else rng
        self.state = self.trainer.init_state(rng, sample_input)
        return self

    def _require_state(self):
        if self.state is None:
            raise RuntimeError("call bind() (or fit/load_checkpoint) first")

    def get_params(self):
        self._require_state()
        return jax.tree.map(lambda a: np.asarray(a[0, 0]),
                            self.state.params)

    # ---- training (reference module.fit) ----------------------------------

    def fit(self, train_data: Tuple[np.ndarray, np.ndarray],
            eval_data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
            num_epoch: int = 1, batch_size: int = 32,
            eval_metric: Union[str, Sequence[str]] = "acc",
            split_by_class: bool = False, augment: bool = False,
            epoch_end_callback: Optional[Callable] = None,
            verbose: bool = True):
        x, y = train_data
        if self.state is None:
            self.bind(x[:2])
        loader = self.trainer.make_loader(x, y, batch_size,
                                          split_by_class=split_by_class,
                                          augment=augment)
        for epoch in range(num_epoch):
            for xb, yb in loader.epoch(epoch):
                self.state, m = self.trainer.train_step(self.state, xb, yb)
                jax.device_get(m)   # host sync per step (collective safety)
            if eval_data is not None:
                pairs = self.score(eval_data, eval_metric)
                if verbose:
                    msg = " ".join(f"{n}={v:.4f}" for n, v in pairs)
                    print(f"Epoch[{epoch}] Validation {msg}", flush=True)
            if epoch_end_callback is not None:
                epoch_end_callback(epoch, self)
        return self

    # ---- inference (reference module.predict / score) ---------------------

    def predict(self, x: np.ndarray, batch_size: int = 512) -> np.ndarray:
        """Logits for a host batch — Trainer's jitted eval path."""
        self._require_state()
        return self.trainer.predict_logits(self.state, np.asarray(x),
                                           batch_size=batch_size)

    def score(self, eval_data: Tuple[np.ndarray, np.ndarray],
              eval_metric: Union[str, Sequence[str]] = "acc"):
        """(name, value) pairs, like the reference's module.score."""
        self._require_state()
        m = metric_mod.create(list(eval_metric) if isinstance(
            eval_metric, (list, tuple)) else eval_metric)
        x, y = eval_data
        logits = self.predict(x)
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        m.update(np.asarray(y), probs)
        return m.get_name_value()

    # ---- checkpointing (reference mx.model save/load_checkpoint) ----------

    def save_checkpoint(self, prefix: str, epoch: int) -> str:
        # no step= here: that argument nests the file under a step_N
        # directory (for periodic in-training snapshots); the epoch already
        # names this file, reference-style (prefix-%04d)
        self._require_state()
        return save_checkpoint(f"{prefix}-{epoch:04d}.ckpt", self.state)

    def load_checkpoint(self, prefix: str, epoch: int,
                        sample_input: np.ndarray):
        """Restore a checkpoint into a freshly-bound state (shapes come
        from ``sample_input``, values from the file)."""
        self.bind(sample_input)
        self.state = load_checkpoint(f"{prefix}-{epoch:04d}.ckpt",
                                     target=self.state)
        return self
