"""P3 — Priority-based Parameter Propagation.

Reference semantics: large tensors are sliced into chunks
(src/kvstore/kvstore_dist.h:835-872 — slice size ``bigarray_bound / 2``)
and every chunk is tagged with its layer's priority (the python worker
pushes with ``priority=-idx``, examples/cnn.py:124-125); the send queue is
a priority queue ordered by that tag
(3rdparty/ps-lite/include/ps/internal/threadsafe_queue.h:19-60), so
front-layer parameters win the wire and the next iteration's forward pass
can start before the rest have synced.

TPU mapping: within one jitted step XLA already schedules collectives to
overlap compute, and per-layer ordering is expressed by putting each
layer's collective adjacent to its consumer.  The explicit queue/slicer
here drives the *host-side* async store (``geomx_tpu.store``), which does
move tensors one message at a time and benefits from exactly the
reference's ordering.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import threading
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Chunk:
    key: Any            # tensor key
    index: int          # chunk number within the tensor
    num_chunks: int
    start: int          # flat element offset
    stop: int
    priority: int       # higher = sent earlier


class P3Slicer:
    """Slice flat tensors into priority-tagged chunks.

    ``slice_elems`` mirrors the reference's ``bigarray_bound / 2`` default
    chunking of big tensors (kvstore_dist.h:858-869).
    """

    def __init__(self, slice_elems: int = 500_000):
        if slice_elems < 1:
            raise ValueError("slice_elems must be >= 1")
        self.slice_elems = int(slice_elems)

    def chunks(self, key: Any, size: int, priority: int = 0) -> List[Chunk]:
        num = max(1, -(-size // self.slice_elems))
        out = []
        for i in range(num):
            start = i * self.slice_elems
            stop = min(size, start + self.slice_elems)
            out.append(Chunk(key=key, index=i, num_chunks=num,
                             start=start, stop=stop, priority=priority))
        return out

    @staticmethod
    def reassemble(size: int, pieces: Sequence[Tuple[Chunk, np.ndarray]]) -> np.ndarray:
        out = np.zeros((size,), dtype=pieces[0][1].dtype if pieces else np.float32)
        seen = 0
        for chunk, data in pieces:
            out[chunk.start:chunk.stop] = data
            seen += chunk.stop - chunk.start
        if seen != size:
            raise ValueError(f"reassembled {seen} of {size} elements")
        return out


class ChunkAssembler:
    """Reassemble a chunked tensor stream — the receive half of P3,
    shared by the server's push reassembly and the client's pull-reply
    reassembly so the chunk wire protocol has one source of truth.

    ``feed(meta, piece)`` folds one chunk in and returns the completed
    tensor (reshaped) when the set completes, else None.  The assembly
    signature is (n_total, num_chunks, gen): a sender that re-slices a
    NEWER value (e.g. a retransmit-triggered second reply) bumps ``gen``,
    which resets the assembly — stale and fresh chunks must never blend
    into a torn tensor.

    ``clear_on_complete=False`` keeps the buffer after completion (the
    server's merge path clears explicitly only once the merge really
    happened, so a retransmitted final chunk can retry after a failure).
    """

    def __init__(self, clear_on_complete: bool = True,
                 monotonic_gen: bool = False):
        """``monotonic_gen=True``: generations are ordered (per-key push
        rounds); a chunk from an OLDER generation than the current
        assembly is dropped instead of resetting it — a stale straggler
        block must never destroy a fresh round's arrived chunks."""
        self.clear_on_complete = clear_on_complete
        self.monotonic_gen = monotonic_gen
        self._st: Optional[dict] = None

    @property
    def gen(self):
        """The in-flight assembly's generation (None if empty)."""
        return None if self._st is None else self._st["sig"][2]

    def feed(self, meta: dict, piece: np.ndarray):
        n = int(meta["n_total"])
        num = int(meta["num_chunks"])
        # pushes carry the key round, pull replies a reply generation —
        # either way a chunk from a different transfer resets the set
        sig = (n, num, meta.get("gen", meta.get("round")))
        if self._st is not None and self._st["sig"] != sig:
            if self.monotonic_gen and isinstance(sig[2], int) \
                    and isinstance(self._st["sig"][2], int) \
                    and sig[2] < self._st["sig"][2]:
                return None  # stale straggler: drop, keep the fresh set
            self._st = None
        if self._st is None:
            self._st = {"sig": sig, "buf": np.zeros((n,), np.float32),
                        "got": set(), "shape": tuple(meta["shape"])}
        st = self._st
        flat = np.asarray(piece, np.float32).reshape(-1)
        start = int(meta["start"])
        st["buf"][start:start + flat.size] = flat
        st["got"].add(int(meta["chunk"]))
        if len(st["got"]) < num:
            return None
        out = st["buf"].reshape(st["shape"])
        if self.clear_on_complete:
            self._st = None
        return out

    def force(self):
        """Finalize an INCOMPLETE assembly: the buffer as-is, with
        never-arrived chunks as zeros — the best-effort DGT semantics
        where a lost low-contribution block is simply gone.  Returns
        None if nothing was fed.  Clears the assembly."""
        if self._st is None:
            return None
        out = self._st["buf"].reshape(self._st["shape"])
        self._st = None
        return out


class PrioritySendQueue:
    """Thread-safe max-priority queue with FIFO tie-breaking.

    Functional equivalent of the reference's ThreadsafeQueue whose Pop
    always takes the highest ``meta.priority`` message
    (threadsafe_queue.h:50-58).
    """

    def __init__(self):
        self._heap: list = []
        self._count = itertools.count()
        self._cv = threading.Condition()
        self._closed = False

    def push(self, item: Any, priority: int = 0) -> None:
        with self._cv:
            if self._closed:
                raise RuntimeError("queue closed")
            heapq.heappush(self._heap, (-priority, next(self._count), item))
            self._cv.notify()

    def pop(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Highest-priority item; FIFO among equals. None on close/timeout."""
        with self._cv:
            while not self._heap and not self._closed:
                if not self._cv.wait(timeout=timeout):
                    return None
            if not self._heap:
                return None
            return heapq.heappop(self._heap)[2]

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def __len__(self) -> int:
        with self._cv:
            return len(self._heap)
