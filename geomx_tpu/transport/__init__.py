"""Transport-layer scheduling equivalents.

The reference implements three transport accelerators inside its forked
ps-lite (P3 priority propagation, DGT multi-channel QoS, TSEngine adaptive
overlays).  On TPU the synchronous data path needs none of them — XLA's
latency-hiding scheduler overlaps collectives with compute — but their
*scheduling logic* remains valuable for the host-side asynchronous modes
and is implemented here as standalone, fully-tested components.
"""

from geomx_tpu.transport.p3 import (ChunkAssembler, P3Slicer,
                                    PrioritySendQueue)
from geomx_tpu.transport.tsengine import TSEngineScheduler

__all__ = ["ChunkAssembler", "P3Slicer", "PrioritySendQueue",
           "TSEngineScheduler"]
