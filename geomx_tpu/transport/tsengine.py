"""TSEngine — throughput-adaptive communication-overlay scheduling.

Reference semantics (3rdparty/ps-lite/src/van.cc:1192-1551): a central
scheduler holds

- ``A[i][j]`` — measured throughput from node i to node j (reported
  piggy-backed on each ASK),
- ``B[j]``   — busy flags: nodes already reached this dissemination round,
- ``lifetime[i][j]`` — the round a measurement was taken (staleness),
- ``iters``  — the dissemination round counter.

*Pull/dissemination* (ProcessAskCommand, van.cc:1358-1435): when a node
holding fresh data ASKs for a receiver, the scheduler answers with an
epsilon-greedy choice: with probability ``min(known/(known+unknown),
max_greed_rate)`` pick the non-busy receiver with the highest measured
throughput from the asker; otherwise pick a random non-busy receiver
(exploration).  When every worker is marked busy the round is over, flags
reset, ``iters`` advances, and askers on an old version are told -1 (stop).

*Push/aggregation* (ProcessAsk1Command, van.cc:1240-1296): nodes that
finished local work queue up; the scheduler pairs them two at a time and
directs the lower-throughput one to send to the higher-throughput one
(relay merge), with node 0 (the server) as the final sink — a dynamically
chosen aggregation tree replacing static fan-in.

This module is the pure scheduling brain (deterministic, seedable,
testable); the host-side async store drives it with real transfer
measurements.
"""

from __future__ import annotations

import random
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

STOP = -1


class TSEngineScheduler:
    def __init__(self, num_nodes: int, max_greed_rate: float = 0.9,
                 seed: Optional[int] = None):
        """``num_nodes`` counts the participating receivers (workers in the
        intra-party instance, parties in the global instance).
        ``max_greed_rate`` mirrors MAX_GREED_RATE_TS (van.cc:447-454)."""
        if num_nodes < 1:
            raise ValueError("num_nodes must be >= 1")
        self.n = num_nodes
        self.max_greed_rate = float(max_greed_rate)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # A[i][j]: last measured throughput i -> j; None = never measured
        self.A: List[List[Optional[float]]] = [
            [None] * num_nodes for _ in range(num_nodes)]
        self.lifetime: List[List[int]] = [[-1] * num_nodes for _ in range(num_nodes)]
        self.busy: List[bool] = [False] * num_nodes
        self.iters = 0
        # push pairing queue (ASK1)
        self._ask_q: deque = deque()
        self._push_done: List[bool] = [False] * num_nodes
        # per-key ASK1 round state (ask1_key)
        self._push_keys: Dict = {}

    # ---- dissemination (pull) ---------------------------------------------

    def report(self, sender: int, receiver: int, throughput: float,
               version: int) -> None:
        """Record a measured transfer (piggy-backed on ASK in the reference)."""
        with self._lock:
            self.A[sender][receiver] = float(throughput)
            self.lifetime[sender][receiver] = version

    def ask(self, sender: int, version: int) -> int:
        """Next receiver for `sender`'s fresh update, or STOP.

        Mirrors ProcessAskCommand: round bookkeeping, then epsilon-greedy
        receiver choice among non-busy nodes.
        """
        with self._lock:
            if all(self.busy):
                self.busy = [False] * self.n
                self.iters += 1
            if version <= self.iters:
                return STOP
            known = [j for j in range(self.n)
                     if not self.busy[j] and self.A[sender][j] is not None]
            unknown = [j for j in range(self.n)
                       if not self.busy[j] and self.A[sender][j] is None]
            if not known and not unknown:
                return STOP
            greed = len(known) / (len(known) + len(unknown))
            greed = min(greed, self.max_greed_rate)
            if known and self._rng.random() < greed:
                receiver = max(known, key=lambda j: self.A[sender][j])
            else:
                receiver = self._rng.choice(unknown or known)
            self.busy[receiver] = True
            return receiver

    # ---- aggregation pairing (push) ---------------------------------------

    def ask1_key(self, node: int, key,
                 num_pushers: int) -> Optional[Tuple[int, int]]:
        """Per-key Ask1 pairing round (ProcessAsk1Command, van.cc:1238-1296,
        redesigned with per-key state instead of the reference's global
        FIFO so concurrent keys cannot cross-pair).

        ``node`` (1-based; 0 is the sink/server) announces it holds a
        partial aggregate of ``key``.  Returns a directive (sender,
        receiver) when a pairing is decided, else None (wait).  Each
        pairing removes one holder; after num_pushers-1 pairings the last
        holder is directed to the sink (0) and the round resets.  Repeat
        asks while a node is already queued are ignored (reference's
        ask_q dedup), so one directive disposes a node's whole merged
        buffer."""
        with self._lock:
            st = self._push_keys.setdefault(
                key, {"q": deque(), "pairs": 0})
            if node in st["q"]:
                return None
            if st["pairs"] >= num_pushers - 1:
                # the final merged holder: everything reduces to the sink
                st["pairs"] = 0
                st["q"].clear()
                return (node, 0)
            st["q"].append(node)
            if len(st["q"]) < 2:
                return None
            a = st["q"].popleft()
            b = st["q"].popleft()
            ab = self.A[a][b] if self.A[a][b] is not None else -1.0
            ba = self.A[b][a] if self.A[b][a] is not None else -1.0
            # the node with the better measured path to its partner sends
            sender, receiver = (a, b) if ab > ba else (b, a)
            st["pairs"] += 1
            return (sender, receiver)

    def drain_key(self, key) -> List[int]:
        """Abort the key's pairing round (a relay failed): return every
        still-queued node — the caller directs them straight to the sink
        — and reset the round state so the next round starts clean."""
        with self._lock:
            st = self._push_keys.get(key)
            if st is None:
                return []
            queued = list(st["q"])
            st["q"].clear()
            st["pairs"] = 0
            return queued

    def ask1(self, node: int) -> Optional[Tuple[int, int]]:
        """Node reports its partial aggregate is ready; returns a directed
        pair (sender, receiver) once two nodes are queued, else None.

        Node 0 is the sink: anything paired with 0 sends to 0
        (ProcessAsk1Command, van.cc:1254-1271); otherwise the
        lower-measured-throughput node sends to the other.
        """
        with self._lock:
            if len(self._ask_q) == 1 and self._ask_q[0] == node:
                return None
            self._ask_q.append(node)
            if len(self._ask_q) < 2:
                return None
            a = self._ask_q.popleft()
            b = self._ask_q.popleft()
            if a == 0 or b == 0:
                sender, receiver = (b, a) if a == 0 else (a, b)
            else:
                ab = self.A[a][b] if self.A[a][b] is not None else -1.0
                ba = self.A[b][a] if self.A[b][a] is not None else -1.0
                sender, receiver = (a, b) if ab > ba else (b, a)
            self._push_done[sender] = True
            if all(self._push_done[1:]):
                self._push_done = [False] * self.n
            return sender, receiver
