"""Per-party serving replica: the local dense copy deltas stream into.

A :class:`ServingReplica` holds the dense fp32 params one party serves
inference from.  It is fed two ways: a full base install (once, at
version publish) and O(k) sparse pair deltas (every training round
after).  Three properties matter more than anything else here:

- **atomic swap, zero downtime**: a delta is applied to a COPY of the
  target layer and the params dict reference swaps once under the
  lock — the gateway's forward pass always reads a complete,
  internally-consistent weight set, never a torn refresh (and a
  restarting replica keeps serving its stale copy while it re-syncs);
- **idempotent apply**: the replica dedups on the same ``(layer,
  round)`` key the registry journals, so a refresh stream replayed
  after a registry failover (or a session resume re-push) cannot
  double-apply — with add semantics a double-apply is silent weight
  corruption, not an error;
- **restart detection**: every refresh reply carries the registry's
  generation token; a change means the registry restarted, and
  :meth:`sync` re-pulls from the replica's own watermarks — the
  replica's dedup absorbs whatever the fresh registry re-sends;
- **per-layer watermarks**: a training round is one PUSH per layer,
  so the registry can transiently hold round N for layer A but not
  yet layer B.  :meth:`sync` therefore sends a per-layer ``since``
  map (last round applied to THAT layer), and the registry filters
  its pending plan per layer — a sync landing mid-round re-pulls the
  straggler layer's round-N delta on the next refresh instead of
  filtering it out behind a global round cursor forever.

Freshness is tracked as both the last applied round and wall-clock
seconds since the last successful refresh (``staleness_s``) — the
numbers the scheduler's ``/healthz`` serving surface and the
``geomx_serve_replica_staleness_seconds`` gauge report.

Host-plane Python only (numpy, no jax): the gateway converts to device
arrays at its own boundary.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomx_tpu.compression.sparseagg import (decode_pairs_payload,
                                             densify_pairs_host)
from geomx_tpu.serve.registry import RegistryClient


def _scatter_inplace(flat: np.ndarray, vals: np.ndarray,
                     idx: np.ndarray) -> None:
    """In-order pair scatter-add into ``flat`` — np.add.at semantics
    (sentinels idx<0 dropped, duplicates sum sequentially).  Routed
    through the nogil native runtime when built; the numpy fallback is
    bit-identical float32 by construction (same sequential fold)."""
    try:
        from geomx_tpu.runtime.native import scatter_pairs
        if scatter_pairs(flat, vals, idx) is not None:
            return
    except (ImportError, ValueError):
        pass
    densify_pairs_host(vals, idx, flat.size, out=flat)


class ServingReplica:
    """One party's serving copy of one published version."""

    def __init__(self, version: str, party: int = 0):
        self.version = str(version)
        self.party = int(party)
        self._lock = threading.Lock()
        self._params: Dict[str, np.ndarray] = {}    # layer -> shaped fp32
        self._order: List[str] = []
        self._applied: set = set()                  # {(layer, round)}
        self._layer_rounds: Dict[str, int] = {}     # layer -> last applied
        self._last_round = 0
        self._gen: Optional[int] = None
        self._refresh_mono = 0.0     # monotonic: wall steps must not
        #                              corrupt the staleness bound
        # O(k) refresh fast path (docs/serving.md "Serving fast path"):
        # the flat buffer WE allocated backing the published view (None
        # when the layer came straight from a base install — that array
        # may alias a read-only wire buffer we must never scatter into),
        # and the retired previous buffer, which lags the published
        # value by EXACTLY the one delta recorded next to it.
        self._pub_flat: Dict[str, Optional[np.ndarray]] = {}
        self._spare: Dict[str, Tuple[np.ndarray, np.ndarray,
                                     np.ndarray]] = {}
        self.refreshes = 0
        self.deltas_applied = 0
        self.replays_deduped = 0
        self.restarts_detected = 0
        self.o1_applies = 0          # O(k) scatter-into-spare refreshes
        self.dense_copies = 0        # O(n) copy fallbacks

    # ---- feeds -------------------------------------------------------------

    def install_base(self, layer: str, arr: np.ndarray, order: int,
                     shape: Optional[Tuple[int, ...]] = None) -> None:
        arr = np.asarray(arr, np.float32)
        if shape is not None:
            arr = arr.reshape(tuple(shape))
        with self._lock:
            if layer not in self._params:
                while len(self._order) <= order:
                    self._order.append(None)
                self._order[order] = layer
            self._params = dict(self._params)       # copy-on-write swap
            self._params[layer] = np.ascontiguousarray(arr)
            # the base may alias the (read-only) wire buffer: not ours
            # to scatter into, and any retired spare is now stale
            self._pub_flat[layer] = None
            self._spare.pop(layer, None)
            self._layer_rounds.setdefault(layer, 0)
            self._refresh_mono = time.monotonic()

    def apply_delta(self, layer: str, round_id: int, vals: np.ndarray,
                    idx: np.ndarray) -> bool:
        """One pair delta onto a private copy of the layer, then swap.
        False = deduped replay (already applied, nothing changed).

        The hot path is O(k), not O(n): every publish retires the
        previous flat buffer next to the one delta it is missing, so
        the NEXT apply replays that single delta into the retired
        buffer (O(k)), scatters the new delta (O(k)), and republishes
        it — two buffers ping-pong per layer, no per-delta dense copy.
        Safety gate: the retired buffer is reused only when its
        refcount proves no reader still holds the old params dict (a
        forward pass mid-batch, a snapshot in a test) — otherwise this
        apply falls back to the O(n) dense copy, counted in
        ``dense_copies``.  Both paths run the identical sequence of
        in-order float32 scatter-adds, so the served weights are
        bit-exact against a dense checkpoint either way."""
        with self._lock:
            if (layer, int(round_id)) in self._applied:
                self.replays_deduped += 1
                return False
            cur = self._params[layer]
            vals = np.ascontiguousarray(vals, np.float32).reshape(-1)
            idx = np.ascontiguousarray(idx, np.int64).reshape(-1)
            if idx.size and int(idx.max()) >= cur.size:
                raise ValueError(
                    f"delta index {int(idx.max())} out of range for "
                    f"size-{cur.size} layer {layer!r}")
            new_flat = None
            sp = self._spare.pop(layer, None)
            if sp is not None:
                flat, mv, mi = sp
                # refs on flat right now: the sp tuple, the local name,
                # and getrefcount's own argument = 3.  Anything above
                # that is the retired published view (alive inside a
                # reader-held params dict) still pinning its base —
                # writing would tear that reader's forward pass.
                if flat.size == cur.size \
                        and sys.getrefcount(flat) <= 3:
                    _scatter_inplace(flat, mv, mi)   # catch up: the one
                    #                                  delta it missed
                    _scatter_inplace(flat, vals, idx)
                    new_flat = flat
                    self.o1_applies += 1
                # else: drop the blocked spare — the buffer retired
                # below replaces it (missing exactly this delta)
            if new_flat is None:
                new_flat = cur.reshape(-1).copy()
                _scatter_inplace(new_flat, vals, idx)
                self.dense_copies += 1
            prev = self._pub_flat.get(layer)
            if prev is not None and prev.size == cur.size \
                    and prev is not new_flat:
                self._spare[layer] = (prev, vals.copy(), idx.copy())
            self._pub_flat[layer] = new_flat
            self._params = dict(self._params)
            self._params[layer] = new_flat.reshape(cur.shape)
            self._applied.add((layer, int(round_id)))
            self._layer_rounds[layer] = max(
                self._layer_rounds.get(layer, 0), int(round_id))
            self._last_round = max(self._last_round, int(round_id))
            self.deltas_applied += 1
            self._refresh_mono = time.monotonic()
        # telemetry outside the lock: the per-layer watermark gauge is
        # how any scrape reader sees sync progress (the map itself was
        # invisible outside the lock until now), and the propagation
        # tracker's "apply" hop anchors the gradient-to-inference join
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().gauge(
                "geomx_serve_replica_round",
                "Last training round applied to each serving layer",
                ("layer",)).labels(layer=layer).set(int(round_id))
        except Exception:
            pass
        try:
            from geomx_tpu.telemetry.fleetscope import note_propagation
            note_propagation(int(round_id), "apply")
        except Exception:
            pass
        return True

    def sync(self, client: RegistryClient) -> dict:
        """One refresh round-trip: pull everything after our per-layer
        watermarks (plus the base if we have nothing yet), apply with
        dedup, adopt the registry's generation token.  A token change
        is a detected restart — counted, and harmless, because the
        pull already asked from OUR watermarks, not the registry's.

        The since map is per layer — a train-while-serving sync that
        lands mid-round (registry holds round N for layer A, layer B
        still in flight) leaves layer B's watermark at N-1, so B's
        round-N delta is still pending on the next pull even though
        the replica's global round already reads N."""
        with self._lock:
            since_layers = dict(self._layer_rounds)
            since = min(since_layers.values(), default=0)
            need_base = not self._params
            prev_gen = self._gen
        frames, tail = client.pull_updates(self.version, since,
                                           need_base=need_base,
                                           since_layers=since_layers)
        applied = deduped = 0
        for msg in frames:
            _v, _, layer = (msg.key or "").partition("/")
            if msg.meta.get("base"):
                self.install_base(layer, msg.array,
                                  int(msg.meta.get("order", 0)),
                                  shape=tuple(msg.meta.get("shape", ())))
                applied += 1
            else:
                vals, idx = decode_pairs_payload(msg.array)
                if self.apply_delta(layer, int(msg.meta["round"]),
                                    vals, idx):
                    applied += 1
                else:
                    deduped += 1
        gen = tail.get("gen")
        with self._lock:
            if prev_gen is not None and gen is not None \
                    and gen != prev_gen:
                self.restarts_detected += 1
            self._gen = gen
            self._refresh_mono = time.monotonic()
            self.refreshes += 1
        return {"frames": len(frames), "applied": applied,
                "deduped": deduped, "gen": gen,
                "registry_last_round": tail.get("last_round"),
                "restart_detected": prev_gen is not None
                and gen is not None and gen != prev_gen}

    # ---- reads -------------------------------------------------------------

    def params(self) -> Dict[str, np.ndarray]:
        """The CURRENT complete weight set (an immutable-by-convention
        dict reference — the swap discipline means a caller may keep
        using it for a whole forward pass)."""
        with self._lock:
            return self._params

    def layer_order(self) -> List[str]:
        with self._lock:
            return [l for l in self._order if l is not None]

    def last_round(self) -> int:
        with self._lock:
            return self._last_round

    def layer_rounds(self) -> Dict[str, int]:
        """Per-layer applied-round watermarks (a copy) — the freshness
        provenance both inference doors stamp onto replies."""
        with self._lock:
            return dict(self._layer_rounds)

    def generation(self) -> Optional[int]:
        with self._lock:
            return self._gen

    def staleness_s(self, now: Optional[float] = None) -> float:
        """Seconds since the last successful refresh, on the MONOTONIC
        clock (``now``, when given, must be a ``time.monotonic()``
        instant) — an NTP wall-clock step mid-run must not fake a
        freshness violation or mask a real one."""
        with self._lock:
            if not self._refresh_mono:
                return float("inf")
            return max(0.0, (time.monotonic() if now is None else now)
                       - self._refresh_mono)

    def snapshot(self) -> dict:
        """The ``/healthz`` serving-surface row for this replica."""
        with self._lock:
            staleness = (float("inf") if not self._refresh_mono
                         else max(0.0,
                                  time.monotonic() - self._refresh_mono))
            return {"version": self.version, "party": self.party,
                    "layers": len(self._params),
                    "last_round": self._last_round,
                    "layer_rounds": dict(self._layer_rounds),
                    "generation": self._gen,
                    "staleness_s": (None if staleness == float("inf")
                                    else round(staleness, 3)),
                    "refreshes": self.refreshes,
                    "deltas_applied": self.deltas_applied,
                    "replays_deduped": self.replays_deduped,
                    "restarts_detected": self.restarts_detected,
                    "o1_applies": self.o1_applies,
                    "dense_copies": self.dense_copies}
