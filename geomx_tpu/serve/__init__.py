"""Geo-distributed serving plane (ROADMAP item 1, docs/serving.md).

The training side of the repo moves sparse gradient rounds; this
package moves the *result* of those rounds to where inference traffic
is.  Three pieces, layered strictly on existing planes:

- :mod:`~geomx_tpu.serve.registry` — the published-model store: a
  crash-recoverable :class:`~geomx_tpu.resilience.durability.DurableStateStore`
  journal of ONE dense base snapshot per version plus sparse
  pair-format deltas (the PR 12 pair codec), replicated to serving
  parties over the binary wire with P3 early-layer-first refresh and
  generation-token restart detection;
- :mod:`~geomx_tpu.serve.replica` — the per-party serving copy:
  applies O(k) pair deltas with the same (sender, rid)/round dedup the
  training wire uses, swaps params atomically so inference never reads
  a torn refresh, and tracks freshness;
- :mod:`~geomx_tpu.serve.gateway` — the inference front door:
  ``POST /infer`` on the shared HTTP exporter, request coalescing into
  a bounded queue, a continuous-batching worker dispatching jit'd
  forward passes at padded bucket sizes (bounded jit cache), and the
  per-request causal ledger (enqueue -> batch -> forward -> reply).

Everything at module scope here is host-plane Python — no jax import
(the scheduler process reads :func:`serving_surface` for its
``/healthz`` body and deliberately never imports jax; only the
gateway's forward path touches jax, lazily).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

# ---------------------------------------------------------------------------
# the serving surface the scheduler's /healthz reports: whichever
# gateway/replica runs in this process registers a zero-arg snapshot
# callable; the scheduler (jax-free) reads it lazily and best-effort
# ---------------------------------------------------------------------------

_surface_lock = threading.Lock()
_surface_fns: Dict[str, Callable[[], Dict[str, Any]]] = {}


def register_serving_surface(name: str,
                             fn: Optional[Callable[[], Dict[str, Any]]]
                             ) -> None:
    """Install (or, with ``fn=None``, remove) a named serving-surface
    snapshot provider.  The scheduler's ``/healthz`` merges every
    registered provider's dict under ``"serving"``."""
    with _surface_lock:
        if fn is None:
            _surface_fns.pop(name, None)
        else:
            _surface_fns[name] = fn


def serving_surface() -> Optional[Dict[str, Any]]:
    """The merged serving snapshot, or None when nothing serves in this
    process.  Provider failures are isolated per name — one broken
    snapshot must not blank the whole health surface."""
    with _surface_lock:
        fns = dict(_surface_fns)
    if not fns:
        return None
    out: Dict[str, Any] = {}
    for name, fn in sorted(fns.items()):
        try:
            out[name] = fn()
        except Exception as e:
            out[name] = {"error": repr(e)}
    return out


def reset_serving_surface() -> None:
    """Drop every registered provider (test isolation)."""
    with _surface_lock:
        _surface_fns.clear()
