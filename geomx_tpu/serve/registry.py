"""Published-model registry: dense base once, sparse pair deltas forever.

The registry is the serving plane's source of truth for model weights
(docs/serving.md "Model registry").  ``publish(version, params)``
journals one dense fp32 base snapshot per version into a
:class:`~geomx_tpu.resilience.durability.DurableStateStore`; every
training round after that appends a **sparse pair-format delta** —
``(values, indices)`` through the PR 12 pair codec
(:func:`~geomx_tpu.compression.sparseagg.encode_pairs_payload`) — so a
replica refresh applies O(k) work per round
(:func:`~geomx_tpu.compression.sparseagg.densify_pairs_host` add
semantics, never a full checkpoint), and ``materialize()`` reconstructs
the dense params bit-exactly by replaying the same adds in the same
order.

Crash story (identical to the host-plane PS tier, PR 10/11): every
base layer and delta is a journal record; a restart replays snapshot +
journal, a torn tail truncates, and the persisted **generation token**
bumps once per process start — refresh replies carry it, so a replica
detects the restart and re-syncs from its last applied round instead
of trusting a reset peer.  A replayed delta push (session resume or
failover re-push) dedups on BOTH the ``(sender, rid)`` pair and the
``(layer, round)`` pair — double-apply would silently corrupt weights
with add semantics, so idempotence is load-bearing here, not polish.

Refresh ordering is P3-style (PAPER.md §5): the pending-delta plan is
**layer-major, publish order first** — early layers land before late
ones, so a pipelined consumer can start its forward pass while the
tail of the model is still on the wire.

The wire is the PR 15 binary codec (:class:`~geomx_tpu.service.protocol.Msg`
frames — no pickle anywhere on this path, GX-WIRE-001 clean); every
PUSH/PULL_REPLY carries ``meta["round"]`` + ``meta["wire_declared"]``
so the fleet round ledger's byte-true accounting covers model refresh
exactly like gradient rounds.  Host-plane Python only — no jax import.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomx_tpu.compression.sparseagg import (PAIR_WIRE_MAX_N,
                                             decode_pairs_payload,
                                             densify_pairs_host,
                                             encode_pairs_payload)
from geomx_tpu.resilience.durability import DurableStateStore
from geomx_tpu.service.protocol import (Msg, MsgType, connect_retry,
                                        recv_frame, send_frame)

STORE_NAME = "registry"


class _VersionState:
    """One published version's accumulating state (registry-lock owned)."""

    __slots__ = ("base", "shapes", "order", "deltas", "applied", "rids",
                 "last_round", "published_unix", "delta_frames")

    def __init__(self):
        self.base: Dict[str, np.ndarray] = {}       # layer -> flat fp32
        self.shapes: Dict[str, Tuple[int, ...]] = {}
        self.order: List[str] = []                  # publish order == P3
        self.deltas: Dict[str, List[Tuple[int, np.ndarray, np.ndarray]]] \
            = {}                                    # layer -> [(round, v, i)]
        self.applied: set = set()                   # {(layer, round)}
        self.rids: set = set()                      # {(sender, rid)}
        self.last_round = 0
        self.published_unix = 0.0
        self.delta_frames = 0

    def to_state(self) -> dict:
        return {"base": dict(self.base), "shapes": dict(self.shapes),
                "order": list(self.order),
                "deltas": {k: list(v) for k, v in self.deltas.items()},
                "applied": sorted(self.applied),
                "rids": sorted(self.rids),
                "last_round": self.last_round,
                "published_unix": self.published_unix,
                "delta_frames": self.delta_frames}

    @classmethod
    def from_state(cls, st: dict) -> "_VersionState":
        vs = cls()
        vs.base = dict(st["base"])
        vs.shapes = {k: tuple(v) for k, v in st["shapes"].items()}
        vs.order = list(st["order"])
        vs.deltas = {k: [tuple(d) for d in v]
                     for k, v in st["deltas"].items()}
        vs.applied = {tuple(a) for a in st["applied"]}
        vs.rids = {tuple(r) for r in st["rids"]}
        vs.last_round = int(st["last_round"])
        vs.published_unix = float(st["published_unix"])
        vs.delta_frames = int(st.get("delta_frames", 0))
        return vs


class ModelRegistry:
    """The in-process registry core: versions, deltas, dedup, recovery.

    ``durable_dir=None`` runs memory-only (generation fixed at 1 — no
    restart to detect); with a directory every mutation journals BEFORE
    it applies, so the in-memory state is always reconstructible."""

    def __init__(self, durable_dir: Optional[str] = None,
                 name: str = STORE_NAME):
        self._lock = threading.Lock()
        self._versions: Dict[str, _VersionState] = {}
        self.replays_deduped = 0
        self._store: Optional[DurableStateStore] = None
        self.generation = 1
        if durable_dir:
            self._store = DurableStateStore(durable_dir, name)
            snap, records = self._store.load()
            if snap is not None:
                self._versions = {v: _VersionState.from_state(st)
                                  for v, st in snap["versions"].items()}
            for rec in records:
                self._replay(rec)
            self.generation = self._store.bump_generation()

    # ---- recovery ----------------------------------------------------------

    def _replay(self, rec: dict) -> None:
        if rec.get("kind") == "base":
            self._apply_base_locked(rec["v"], rec["l"], rec["arr"],
                                    rec["shape"], rec["order"])
        elif rec.get("kind") == "delta":
            self._apply_delta_locked(rec["v"], rec["l"], rec["r"],
                                     rec["vals"], rec["idx"],
                                     rec.get("s", -1), rec.get("rid"))

    # ---- publish (dense base, once per version) ----------------------------

    def publish_layer(self, version: str, layer: str, arr: np.ndarray,
                      order: int) -> None:
        """One dense base layer.  ``order`` is the layer's position in
        the P3 refresh priority (publish order: early layers first)."""
        flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
        shape = tuple(int(d) for d in np.shape(arr))
        with self._lock:
            if self._store is not None:
                self._store.append({"kind": "base", "v": str(version),
                                    "l": str(layer), "arr": flat,
                                    "shape": list(shape),
                                    "order": int(order)})
            self._apply_base_locked(str(version), str(layer), flat,
                                    shape, int(order))

    def _apply_base_locked(self, version, layer, flat, shape, order):
        vs = self._versions.setdefault(version, _VersionState())
        flat = np.asarray(flat, np.float32).reshape(-1)
        if layer not in vs.base:
            while len(vs.order) <= order:
                vs.order.append(None)
            vs.order[order] = layer
        vs.base[layer] = flat
        vs.shapes[layer] = tuple(shape)
        vs.published_unix = time.time()

    def publish(self, version: str, params: Dict[str, np.ndarray]) -> dict:
        """Publish a whole version: dict insertion order IS the P3
        layer priority.  Returns ``{"layers": n, "dense_bytes": b}``."""
        total = 0
        for i, (layer, arr) in enumerate(params.items()):
            self.publish_layer(version, layer, arr, i)
            total += int(np.asarray(arr).size) * 4
        return {"layers": len(params), "dense_bytes": total}

    # ---- sparse deltas -----------------------------------------------------

    def apply_delta(self, version: str, layer: str, round_id: int,
                    vals: np.ndarray, idx: np.ndarray,
                    sender: int = -1, rid: Optional[int] = None) -> bool:
        """Append one pair-format delta; False when the dedup rejects a
        replay ((sender, rid) already seen, or this (layer, round)
        already applied) — the idempotence every re-push path leans on."""
        version, layer = str(version), str(layer)
        with self._lock:
            vs = self._versions.get(version)
            if vs is None:
                raise KeyError(f"unpublished version {version!r}")
            if layer not in vs.base:
                raise KeyError(f"{version!r} has no base layer {layer!r}")
            if (layer, int(round_id)) in vs.applied or \
                    (rid is not None and (int(sender), str(rid)) in vs.rids):
                self.replays_deduped += 1
                return False
            if int(np.asarray(vals).size) and \
                    vs.base[layer].size > PAIR_WIRE_MAX_N:
                raise ValueError(
                    f"layer {layer!r} exceeds PAIR_WIRE_MAX_N "
                    f"({vs.base[layer].size} > {PAIR_WIRE_MAX_N}); "
                    "publish a fresh dense base instead")
            vals = np.asarray(vals, np.float32).reshape(-1)
            idx = np.asarray(idx).reshape(-1).astype(np.int64)
            if self._store is not None:
                self._store.append({"kind": "delta", "v": version,
                                    "l": layer, "r": int(round_id),
                                    "vals": vals, "idx": idx,
                                    "s": int(sender), "rid": rid})
            self._apply_delta_locked(version, layer, int(round_id),
                                     vals, idx, int(sender), rid)
        # fresh apply = the round's "publish" hop in the gradient-to-
        # inference propagation join (outside the lock, best-effort)
        try:
            from geomx_tpu.telemetry.fleetscope import note_propagation
            note_propagation(int(round_id), "publish")
        except Exception:
            pass
        return True

    def _apply_delta_locked(self, version, layer, round_id, vals, idx,
                            sender, rid):
        vs = self._versions.setdefault(version, _VersionState())
        if (layer, round_id) in vs.applied:
            return  # journal replay of a record the snapshot also covers
        vs.deltas.setdefault(layer, []).append(
            (round_id, np.asarray(vals, np.float32).reshape(-1),
             np.asarray(idx).reshape(-1).astype(np.int64)))
        vs.applied.add((layer, round_id))
        if rid is not None:
            vs.rids.add((sender, str(rid)))
        vs.last_round = max(vs.last_round, round_id)
        vs.delta_frames += 1

    # ---- reads -------------------------------------------------------------

    def materialize(self, version: str) -> Dict[str, np.ndarray]:
        """Dense params: base copy + every delta replayed in application
        order with :func:`densify_pairs_host` add semantics — the same
        scatter-adds a replica ran incrementally, so the bits match a
        dense checkpoint maintained alongside exactly."""
        with self._lock:
            vs = self._versions.get(str(version))
            if vs is None:
                raise KeyError(f"unpublished version {version!r}")
            out: Dict[str, np.ndarray] = {}
            for layer in vs.order:
                if layer is None:
                    continue
                flat = vs.base[layer].copy()
                for _r, vals, idx in vs.deltas.get(layer, ()):
                    densify_pairs_host(vals, idx, flat.size, out=flat)
                out[layer] = flat.reshape(vs.shapes[layer])
            return out

    def pending(self, version: str, since_round: int,
                need_base: bool = False,
                since_layers: Optional[Dict[str, int]] = None
                ) -> List[dict]:
        """The P3 refresh plan: layer-major in publish order (early
        layers first), rounds ascending within a layer; optional dense
        base frames (same priority order) ahead of the deltas.

        ``since_layers`` (replica watermarks, layer -> last applied
        round) filters each layer against ITS OWN cursor; a layer the
        map doesn't mention falls back to ``since_round``.  This is
        what makes a partially-landed round safe: a replica that
        already applied layer A's round N still has layer B at N-1 in
        its map, so B's round-N delta stays pending instead of being
        dropped behind a global ``r > N`` filter."""
        with self._lock:
            vs = self._versions.get(str(version))
            if vs is None:
                return []
            plan: List[dict] = []
            layers = [l for l in vs.order if l is not None]
            if need_base:
                for i, layer in enumerate(layers):
                    plan.append({"layer": layer, "base": True, "order": i,
                                 "round": 0,
                                 "shape": list(vs.shapes[layer]),
                                 "arr": vs.base[layer]})
            for layer in layers:
                cut = int(since_round) if since_layers is None \
                    else int(since_layers.get(layer, since_round))
                for r, vals, idx in vs.deltas.get(layer, ()):
                    if r > cut:
                        plan.append({"layer": layer, "base": False,
                                     "round": r, "vals": vals,
                                     "idx": idx,
                                     "n": int(vs.base[layer].size)})
            plan.sort(key=lambda f: (0 if f["base"] else 1,
                                     layers.index(f["layer"]),
                                     f["round"]))
            return plan

    def info(self) -> dict:
        with self._lock:
            versions = {}
            for v, vs in self._versions.items():
                versions[v] = {
                    "layers": len(vs.base),
                    "last_round": vs.last_round,
                    "delta_frames": vs.delta_frames,
                    "dense_bytes": int(sum(a.size for a in
                                           vs.base.values())) * 4,
                    "published_unix": vs.published_unix,
                }
            return {"versions": versions, "generation": self.generation,
                    "replays_deduped": self.replays_deduped}

    def last_round(self, version: str) -> int:
        with self._lock:
            vs = self._versions.get(str(version))
            return 0 if vs is None else vs.last_round

    # ---- durability --------------------------------------------------------

    def compact(self) -> None:
        """Fold the journal into a snapshot (the registry's equivalent
        of the PS tier's round-gate compaction)."""
        with self._lock:
            if self._store is None:
                return
            self._store.compact({"versions": {
                v: vs.to_state() for v, vs in self._versions.items()}})

    def journal_bytes(self) -> int:
        return 0 if self._store is None else self._store.journal_bytes()

    def close(self) -> None:
        if self._store is not None:
            self._store.close()


# ---------------------------------------------------------------------------
# the replicated wire: RegistryServer serves publish/delta/refresh over
# binary Msg frames; RegistryClient is the training- and replica-side
# stub.  No pickle on this path (GX-WIRE-001).
# ---------------------------------------------------------------------------

class RegistryServer:
    """TCP front for a :class:`ModelRegistry` shard.

    Frames in: PUSH (base layer or pair delta), PULL (refresh since a
    round), COMMAND (``serve_info`` / ``serve_compact``), STOP.  Every
    reply carries ``meta["gen"]`` — the restart token replicas compare.
    ``crash()`` severs sockets abruptly (chaos kill); a replacement
    constructed on the same durable dir is the failover."""

    def __init__(self, durable_dir: Optional[str] = None, port: int = 0,
                 bind_host: Optional[str] = None,
                 registry: Optional[ModelRegistry] = None):
        self.registry = registry if registry is not None \
            else ModelRegistry(durable_dir)
        if bind_host is None:
            # host-plane bind knob, parity with GeoPSServer/GeoScheduler
            # graftlint: disable=GXL006 — host-plane knob
            bind_host = os.environ.get("GEOMX_PS_BIND_HOST", "127.0.0.1")
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        from geomx_tpu.service.server import GeoPSServer
        GeoPSServer._bind_with_retry(self._srv, bind_host, int(port))
        self._srv.listen(64)
        self._srv.settimeout(0.2)
        self.addr = self._srv.getsockname()
        self.port = self.addr[1]
        self._running = True
        self._conns: set = set()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="registry-accept", daemon=True)

    @property
    def generation(self) -> int:
        return self.registry.generation

    def start(self) -> "RegistryServer":
        self._accept_thread.start()
        return self

    # ---- networking --------------------------------------------------------

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)
            self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while self._running:
                msg = recv_frame(conn)
                if msg is None:
                    return
                if not self._dispatch(conn, msg):
                    return
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            self._conns.discard(conn)
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn: socket.socket, msg: Msg) -> bool:
        reg = self.registry
        if msg.type == MsgType.PUSH:
            version, _, layer = (msg.key or "").partition("/")
            meta = msg.meta
            # a bad PUSH (unpublished version/layer, oversized pair
            # payload, garbage frame) must answer with an ERROR frame,
            # not a torn-down socket — the client would otherwise
            # retry the identical frame and surface an opaque
            # ConnectionError instead of the real cause
            try:
                if meta.get("base"):
                    reg.publish_layer(version, layer, msg.array,
                                      int(meta.get("order", 0)))
                    applied = True
                else:
                    vals, idx = decode_pairs_payload(msg.array)
                    applied = reg.apply_delta(
                        version, layer, int(meta["round"]), vals, idx,
                        sender=msg.sender, rid=meta.get("rid"))
            except (KeyError, ValueError, TypeError) as e:
                send_frame(conn, Msg(
                    MsgType.ERROR, sender=-1,
                    meta={"error": f"{type(e).__name__}: {e}",
                          "gen": reg.generation,
                          "rid": meta.get("rid", 0)}))
                return True
            send_frame(conn, Msg(
                MsgType.ACK, sender=-1,
                meta={"gen": reg.generation, "applied": int(applied),
                      "rid": meta.get("rid", 0),
                      "last_round": reg.last_round(version)}))
            return True
        if msg.type == MsgType.PULL:
            version = msg.key or ""
            try:
                since_layers = msg.meta.get("since_layers")
                plan = reg.pending(
                    version, int(msg.meta.get("since", 0)),
                    need_base=bool(msg.meta.get("need_base")),
                    since_layers=since_layers)
                for f in plan:
                    if f["base"]:
                        arr = f["arr"]
                        meta = {"version": version, "base": 1,
                                "order": f["order"], "round": 0,
                                "shape": f["shape"],
                                "wire_declared": int(arr.nbytes)}
                    else:
                        arr = encode_pairs_payload(f["vals"], f["idx"])
                        meta = {"version": version, "base": 0,
                                "round": f["round"], "n": f["n"],
                                "comp": "pairs",
                                "wire_declared": int(arr.nbytes)}
                    send_frame(conn, Msg(MsgType.PULL_REPLY,
                                         key=f"{version}/{f['layer']}",
                                         sender=-1, meta=meta, array=arr))
            except (KeyError, ValueError, TypeError) as e:
                send_frame(conn, Msg(
                    MsgType.ERROR, sender=-1,
                    meta={"error": f"{type(e).__name__}: {e}",
                          "gen": reg.generation,
                          "rid": msg.meta.get("rid", 0)}))
                return True
            send_frame(conn, Msg(
                MsgType.ACK, sender=-1,
                meta={"gen": reg.generation, "frames": len(plan),
                      "rid": msg.meta.get("rid", 0),
                      "last_round": reg.last_round(version)}))
            return True
        if msg.type == MsgType.COMMAND:
            cmd = msg.meta.get("cmd")
            if cmd == "serve_info":
                send_frame(conn, Msg(MsgType.ACK, sender=-1,
                                     meta={"gen": reg.generation,
                                           "info": reg.info()}))
            elif cmd == "serve_compact":
                reg.compact()
                send_frame(conn, Msg(MsgType.ACK, sender=-1,
                                     meta={"gen": reg.generation}))
            else:
                send_frame(conn, Msg(MsgType.ERROR, sender=-1,
                                     meta={"error": f"unknown cmd {cmd!r}"}))
            return True
        if msg.type == MsgType.STOP:
            send_frame(conn, Msg(MsgType.ACK, sender=-1,
                                 meta={"gen": reg.generation}))
            self.stop()
            return False
        send_frame(conn, Msg(MsgType.ERROR, sender=-1,
                             meta={"error": f"unhandled {msg.type.name}"}))
        return True

    # ---- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass
        self.registry.close()

    def crash(self) -> None:
        """Chaos kill: sever every socket abruptly — no drains, nothing
        graceful.  Only the durable dir survives, as for a real kill."""
        self._running = False
        for sock in [self._srv] + list(self._conns):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self.registry.close()

    def join(self, timeout: Optional[float] = None) -> None:
        self._accept_thread.join(timeout)


class RegistryClient:
    """Training- and replica-side stub.  One socket, synchronous
    request/reply; a send that dies mid-flight reconnects and REPLAYS
    the same ``rid`` — the registry's dedup makes the retry exactly-once
    (the kill-mid-refresh pin in tests/test_recovery.py)."""

    def __init__(self, addr: Tuple[str, int], sender: int = 0,
                 timeout_s: float = 30.0):
        self.addr = (str(addr[0]), int(addr[1]))
        self.sender = int(sender)
        self.timeout_s = float(timeout_s)
        # reentrant: publish/push_delta/pull_updates hold it across the
        # whole exchange and mint rids (next_rid) from inside
        self._lock = threading.RLock()
        self._sock: Optional[socket.socket] = None
        self._rid = 0
        self.replays_sent = 0

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = connect_retry(self.addr,
                                       total_timeout_s=self.timeout_s)
            self._sock.settimeout(self.timeout_s)
        return self._sock

    def _drop_conn(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _roundtrip(self, msg: Msg, retries: int = 1) -> Msg:
        """Send one frame, read one reply; on a dead socket reconnect
        and resend the SAME frame (same rid — dedup absorbs it)."""
        for attempt in range(retries + 1):
            try:
                sock = self._conn()
                send_frame(sock, msg)
                rep = recv_frame(sock)
                if rep is None:
                    raise ConnectionError("registry closed mid-reply")
                return rep
            except (ConnectionError, OSError, TimeoutError):
                self._drop_conn()
                if attempt >= retries:
                    raise
                self.replays_sent += 1
        raise ConnectionError("unreachable")

    def next_rid(self) -> int:
        with self._lock:
            self._rid += 1
            return self._rid

    # ---- operations --------------------------------------------------------

    def publish(self, version: str, params: Dict[str, np.ndarray],
                retries: int = 1) -> dict:
        """Dense base snapshot, one PUSH per layer in dict order (the
        P3 priority order)."""
        ack = {}
        with self._lock:
            for i, (layer, arr) in enumerate(params.items()):
                arr = np.ascontiguousarray(arr, np.float32)
                rep = self._roundtrip(Msg(
                    MsgType.PUSH, key=f"{version}/{layer}",
                    sender=self.sender,
                    meta={"base": 1, "order": i, "round": 0,
                          "rid": self.next_rid(),
                          "wire_declared": int(arr.nbytes)},
                    array=arr), retries=retries)
                if rep.type == MsgType.ERROR:
                    raise RuntimeError(
                        rep.meta.get("error", "publish failed"))
                ack = dict(rep.meta)
        return ack

    def push_delta(self, version: str, round_id: int,
                   layers: Dict[str, Tuple[np.ndarray, np.ndarray]],
                   retries: int = 1) -> dict:
        """One training round's sparse delta: one pair-payload PUSH per
        layer.  Returns the last ACK meta (``gen``, ``applied``,
        ``last_round``); raises on an un-retryable wire death."""
        ack = {}
        applied = 0
        with self._lock:
            for layer, (vals, idx) in layers.items():
                payload = encode_pairs_payload(vals, idx)
                rep = self._roundtrip(Msg(
                    MsgType.PUSH, key=f"{version}/{layer}",
                    sender=self.sender,
                    meta={"base": 0, "round": int(round_id),
                          "rid": self.next_rid(), "comp": "pairs",
                          "wire_declared": int(payload.nbytes)},
                    array=payload), retries=retries)
                if rep.type == MsgType.ERROR:
                    raise RuntimeError(rep.meta.get("error", "push failed"))
                ack = dict(rep.meta)
                applied += int(ack.get("applied", 0))
        ack["applied_layers"] = applied
        return ack

    def pull_updates(self, version: str, since_round: int,
                     need_base: bool = False,
                     since_layers: Optional[Dict[str, int]] = None
                     ) -> Tuple[List[Msg], dict]:
        """Refresh stream: every pending frame (base first when asked,
        then deltas in P3 order) plus the terminal ACK meta.
        ``since_layers`` carries the replica's per-layer watermarks so
        a partially-landed round is never filtered out (see
        :meth:`ModelRegistry.pending`)."""
        with self._lock:
            sock = self._conn()
            meta = {"since": int(since_round),
                    "need_base": int(bool(need_base)),
                    "rid": self.next_rid()}
            if since_layers:
                meta["since_layers"] = {str(k): int(v)
                                        for k, v in since_layers.items()}
            send_frame(sock, Msg(
                MsgType.PULL, key=str(version), sender=self.sender,
                meta=meta))
            frames: List[Msg] = []
            while True:
                rep = recv_frame(sock)
                if rep is None:
                    self._drop_conn()
                    raise ConnectionError("registry died mid-refresh")
                if rep.type == MsgType.ACK:
                    return frames, dict(rep.meta)
                if rep.type == MsgType.ERROR:
                    raise RuntimeError(rep.meta.get("error", "pull failed"))
                frames.append(rep)

    def info(self) -> dict:
        rep = self._roundtrip(Msg(MsgType.COMMAND, sender=self.sender,
                                  meta={"cmd": "serve_info"}))
        return dict(rep.meta)

    def compact(self) -> dict:
        rep = self._roundtrip(Msg(MsgType.COMMAND, sender=self.sender,
                                  meta={"cmd": "serve_compact"}))
        return dict(rep.meta)

    def close(self) -> None:
        with self._lock:
            self._drop_conn()
