"""Batched inference gateway: POST /infer -> bounded queue -> jit'd forward.

The gateway is the serving plane's front door (docs/serving.md
"Inference gateway").  Requests arrive over the shared HTTP exporter
(:func:`~geomx_tpu.telemetry.export.start_http_exporter` — the same
plumbing behind the scheduler's ``/metrics``/``/healthz``), coalesce
into a bounded queue, and a continuous-batching worker drains them:

- **coalescing**: the worker takes the first waiting request, then
  keeps absorbing arrivals for at most ``queue_ms`` (or until
  ``max_batch``) — latency is traded for batch efficiency by exactly
  one knob;
- **padded buckets, bounded jit cache**: a batch pads up to the next
  power-of-two bucket ≤ ``max_batch``, so the jit cache holds at most
  ``len(buckets)`` executables per input shape — request count can be
  anything, compile count cannot (the pin in tests/test_serve.py);
- **atomic weights**: the forward reads
  :meth:`~geomx_tpu.serve.replica.ServingReplica.params` once per
  batch — the replica's swap discipline means a mid-batch delta
  refresh changes the NEXT batch's weights, never this one's;
- **deterministic shedding**: the SLO policy's ``set_shed_fraction``
  sheds by fractional accumulator (every shed is an explicit 503 the
  client sees and the ``geomx_serve_requests_total{status="shed"}``
  counter records — a shed request is refused, never lost);
- **causal request ledger**: every request lands in the process-global
  :class:`~geomx_tpu.telemetry.ledger.RequestLedger` with its
  enqueue -> batch -> forward -> reply phase seconds, the p50/p99
  surface ``GET /ledger`` serves.

jax is imported lazily inside the forward path only — constructing a
gateway (or importing this module) in a jax-free process is safe.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from geomx_tpu.serve import register_serving_surface
from geomx_tpu.serve.replica import ServingReplica

BATCH_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


# ---------------------------------------------------------------------------
# params pytree <-> named-layer dict (the registry's schema).  The
# leaf index prefixes (zero-padded to 4 digits, wider when the tree
# needs it) make dict insertion order == pytree leaf order == the P3
# "early layers first" refresh priority; ``unflatten_params`` rebuilds
# by parsing the integer prefix — NOT a lexicographic name sort, which
# would put "10000..." before "9999..." and silently reorder leaves.
# ---------------------------------------------------------------------------

def _leaf_index(name: str) -> int:
    """The integer leaf index a :func:`flatten_params` name starts with."""
    i = 0
    while i < len(name) and name[i].isdigit():
        i += 1
    if i == 0:
        raise ValueError(f"param name {name!r} has no leaf-index prefix")
    return int(name[:i])


def flatten_params(tree) -> Tuple[Dict[str, np.ndarray], Any]:
    """A jax pytree as ``({name: np.float32 array}, treedef)``."""
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    named: Dict[str, np.ndarray] = {}
    for i, (path, leaf) in enumerate(flat):
        name = f"{i:04d}{jax.tree_util.keystr(path)}"
        named[name] = np.asarray(leaf, np.float32)
    return named, treedef


def unflatten_params(treedef, named: Dict[str, np.ndarray]):
    """Inverse of :func:`flatten_params` (names sort by their integer
    leaf-index prefix; the sequence must be contiguous from 0)."""
    import jax
    keys = sorted(named, key=_leaf_index)
    if [_leaf_index(k) for k in keys] != list(range(len(keys))):
        raise ValueError(
            "named params do not form a contiguous 0..n-1 leaf-index "
            "sequence — refusing to rebuild a reordered pytree")
    return jax.tree_util.tree_unflatten(
        treedef, [named[k] for k in keys])


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to (and including) ``max_batch``."""
    out = []
    b = 1
    while b < int(max_batch):
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return tuple(sorted(set(out)))


class _Request:
    __slots__ = ("x", "event", "result", "error", "rid", "t_enqueue",
                 "t_batch", "batch_size", "bucket", "_taken_lock",
                 "_taken")

    def __init__(self, x: np.ndarray, rid: int):
        self.x = x
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[str] = None
        self.rid = rid
        self.t_enqueue = time.time()
        self.t_batch: Optional[float] = None
        self.batch_size = 0
        self.bucket = 0
        self._taken_lock = threading.Lock()
        self._taken = False

    def take(self) -> bool:
        """Claim terminal ownership — exactly one caller wins.  The
        batch worker takes before dispatching; the HTTP thread takes on
        client-deadline expiry — so a request that timed out while
        queued is skipped by a later batch (and counted "timeout"),
        never double-finished or counted "ok" after its 500."""
        with self._taken_lock:
            if self._taken:
                return False
            self._taken = True
            return True


class InferenceGateway:
    """Continuous-batching inference over one serving replica."""

    def __init__(self, replica: ServingReplica, treedef,
                 model_name: str = "mlp", num_classes: int = 10,
                 max_batch: int = 8, queue_ms: float = 2.0,
                 queue_cap: int = 256,
                 buckets: Optional[Tuple[int, ...]] = None,
                 apply_fn: Optional[Callable] = None,
                 request_timeout_s: Optional[float] = None):
        self.replica = replica
        self.treedef = treedef
        self.model_name = str(model_name)
        self.num_classes = int(num_classes)
        self.max_batch = max(1, int(max_batch))
        self.queue_ms = max(0.0, float(queue_ms))
        self.buckets = tuple(sorted(buckets)) if buckets \
            else default_buckets(self.max_batch)
        if self.buckets[-1] < self.max_batch:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} < max_batch "
                f"{self.max_batch}: a full batch would have no bucket")
        if request_timeout_s is None:
            from geomx_tpu.config import GeoConfig
            request_timeout_s = GeoConfig.from_env().serve_timeout_s
        self.request_timeout_s = max(0.001, float(request_timeout_s))
        self._apply_fn = apply_fn          # overrides get_model (tests)
        self._model = None
        self._queue: "queue.Queue[Optional[_Request]]" = \
            queue.Queue(maxsize=max(1, int(queue_cap)))
        self._jit_cache: Dict[tuple, Any] = {}
        self._lock = threading.Lock()
        self._rid = 0
        self._shed_fraction = 0.0
        self._shed_acc = 0.0
        self._running = False
        self._worker: Optional[threading.Thread] = None
        self.requests_ok = 0
        self.requests_shed = 0
        self.requests_error = 0
        self.requests_timeout = 0
        self.batches_dispatched = 0

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> "InferenceGateway":
        self._running = True
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="serve-batcher", daemon=True)
        self._worker.start()
        register_serving_surface("gateway", self.surface_snapshot)
        return self

    def stop(self) -> None:
        self._running = False
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        if self._worker is not None:
            self._worker.join(timeout=10.0)
        register_serving_surface("gateway", None)

    # ---- SLO hooks (control/policy.py SloPolicy actuates these) ------------

    def set_shed_fraction(self, fraction: float) -> None:
        with self._lock:
            self._shed_fraction = min(1.0, max(0.0, float(fraction)))

    def shed_fraction(self) -> float:
        with self._lock:
            return self._shed_fraction

    def serving_stats(self) -> dict:
        """The observation the SLO policy consumes: request-ledger
        percentiles + live queue depth + the current shed fraction."""
        from geomx_tpu.telemetry.ledger import get_request_ledger
        s = get_request_ledger().summary()
        return {"p50_s": s.get("total_p50_s"),
                "p99_s": s.get("total_p99_s"),
                "qps": s.get("qps"),
                "queue_depth": self._queue.qsize(),
                "shed_fraction": self.shed_fraction()}

    # ---- submission --------------------------------------------------------

    def submit(self, x: np.ndarray) -> _Request:
        """Enqueue one example.  A full queue or an active shed marks
        the request shed immediately (explicit refusal, never silent
        loss)."""
        with self._lock:
            self._rid += 1
            rid = self._rid
            shed = False
            if self._shed_fraction > 0.0:
                self._shed_acc += self._shed_fraction
                if self._shed_acc >= 1.0:
                    self._shed_acc -= 1.0
                    shed = True
        req = _Request(np.asarray(x, np.float32), rid)
        if shed:
            self._finish_shed(req)
            return req
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self._finish_shed(req)
            return req
        self._observe_queue_depth()
        return req

    def _finish_shed(self, req: _Request) -> None:
        req.take()          # fresh request, unqueued: always wins
        req.error = "shed"
        req.event.set()
        # every ThreadingHTTPServer thread calls submit concurrently —
        # the counter bump must sit under the gateway lock or the
        # read-modify-write race loses sheds from the zero-lost books
        with self._lock:
            self.requests_shed += 1
        self._count_request("shed")
        self._ledger_observe(req, status="shed", forward_s=0.0,
                             reply_s=0.0)

    def _finish_timeout(self, req: _Request) -> bool:
        """Finish a request whose client deadline expired while it was
        still queued.  False = a batch worker already claimed it (the
        forward is in flight and the result/event are imminent)."""
        if not req.take():
            return False
        req.error = "timeout"
        req.event.set()
        with self._lock:
            self.requests_timeout += 1
        self._count_request("timeout")
        self._ledger_observe(req, status="timeout", forward_s=0.0,
                             reply_s=0.0)
        return True

    # ---- the continuous-batching worker ------------------------------------

    def _worker_loop(self) -> None:
        while self._running:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                return
            batch = [first]
            deadline = time.monotonic() + self.queue_ms / 1000.0
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._dispatch(batch)
                    return
                batch.append(nxt)
            self._dispatch(batch)
        # drain on stop: whatever is queued still gets an answer
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is not None:
                self._dispatch([req])

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def jit_cache_size(self) -> int:
        return len(self._jit_cache)

    def _forward_fn(self, bucket: int, feat_shape: tuple):
        """The jit'd forward for one padded bucket size (bounded cache:
        one executable per (bucket, input feature shape))."""
        key = (int(bucket),) + tuple(feat_shape)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        import jax
        if self._apply_fn is not None:
            # injected forward takes the flat named dict directly (tests
            # and jax-light callers skip the treedef round-trip)
            apply = self._apply_fn

            def fwd(named_params, xb):
                return apply(named_params, xb)
        else:
            if self._model is None:
                from geomx_tpu.models import get_model
                self._model = get_model(self.model_name,
                                        num_classes=self.num_classes)
            model = self._model

            def fwd(named_params, xb):
                variables = unflatten_params(self.treedef, named_params)
                return model.apply(variables, xb, train=False)

        fn = jax.jit(fwd)
        self._jit_cache[key] = fn
        return fn

    def _dispatch(self, batch: List[_Request]) -> None:
        # claim each request first: one that timed out while queued was
        # already finished (500 + "timeout" accounting) by the HTTP
        # thread — running it anyway would count it "ok" after the
        # client gave up
        batch = [r for r in batch if r.take()]
        if not batch:
            self._observe_queue_depth()
            return
        t_batch = time.time()
        n = len(batch)
        bucket = self.bucket_for(n)
        for r in batch:
            r.t_batch = t_batch
            r.batch_size = n
            r.bucket = bucket
        try:
            xb = np.stack([r.x for r in batch]).astype(np.float32)
            if bucket > n:
                pad = np.zeros((bucket - n,) + xb.shape[1:], np.float32)
                xb = np.concatenate([xb, pad], axis=0)
            named = self.replica.params()
            fn = self._forward_fn(bucket, xb.shape[1:])
            t_f0 = time.time()
            out = np.asarray(fn(named, xb))
            forward_s = time.time() - t_f0
            self.batches_dispatched += 1
            self._observe_batch(n)
            t_reply0 = time.time()
            for i, r in enumerate(batch):
                r.result = out[i]
                r.event.set()
            reply_s = time.time() - t_reply0
            for r in batch:
                self.requests_ok += 1
                self._count_request("ok")
                self._ledger_observe(r, status="ok",
                                     forward_s=forward_s,
                                     reply_s=reply_s)
        except Exception as e:
            for r in batch:
                r.error = repr(e)
                r.event.set()
                self.requests_error += 1
                self._count_request("error")
                self._ledger_observe(r, status="error", forward_s=0.0,
                                     reply_s=0.0)
        self._observe_queue_depth()
        self._observe_staleness()

    # ---- telemetry ---------------------------------------------------------

    def _count_request(self, status: str) -> None:
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().counter(
                "geomx_serve_requests_total",
                "Inference requests by terminal status",
                ("status",)).labels(status=status).inc()
        except Exception:
            pass

    def _observe_batch(self, n: int) -> None:
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().histogram(
                "geomx_serve_batch_size",
                "Dispatched inference batch sizes (pre-padding)",
                buckets=BATCH_SIZE_BUCKETS).observe(float(n))
        except Exception:
            pass

    def _observe_queue_depth(self) -> None:
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().gauge(
                "geomx_serve_queue_depth",
                "Inference requests waiting in the gateway queue"
            ).set(float(self._queue.qsize()))
        except Exception:
            pass

    def _observe_staleness(self) -> None:
        try:
            from geomx_tpu.telemetry.registry import get_registry
            s = self.replica.staleness_s()
            if s != float("inf"):
                get_registry().gauge(
                    "geomx_serve_replica_staleness_seconds",
                    "Seconds since the serving replica's last "
                    "successful weight refresh").set(float(s))
        except Exception:
            pass

    def _ledger_observe(self, req: _Request, status: str,
                        forward_s: float, reply_s: float) -> None:
        try:
            from geomx_tpu.telemetry.ledger import get_request_ledger
            t_batch = req.t_batch if req.t_batch is not None \
                else req.t_enqueue
            get_request_ledger().observe(
                rid=req.rid, t_enqueue=req.t_enqueue,
                queue_s=max(0.0, t_batch - req.t_enqueue),
                forward_s=forward_s, reply_s=reply_s,
                batch_size=req.batch_size, bucket=req.bucket,
                status=status)
        except Exception:
            pass

    # ---- surfaces ----------------------------------------------------------

    def surface_snapshot(self) -> dict:
        """The ``/healthz`` serving block: published versions the
        replica tracks, freshness, queue depth, terminal counts."""
        return {"replica": self.replica.snapshot(),
                "queue_depth": self._queue.qsize(),
                "max_batch": self.max_batch,
                "queue_ms": self.queue_ms,
                "request_timeout_s": self.request_timeout_s,
                "buckets": list(self.buckets),
                "jit_cache_size": self.jit_cache_size(),
                "shed_fraction": self.shed_fraction(),
                "requests": {"ok": self.requests_ok,
                             "shed": self.requests_shed,
                             "error": self.requests_error,
                             "timeout": self.requests_timeout},
                "batches": self.batches_dispatched}

    def infer_route(self, body: bytes) -> Tuple[int, bytes, str]:
        """The ``POST /infer`` handler (wire shape in docs/serving.md):
        ``{"inputs": [[...feature vector...], ...]}`` in, one output
        row per input out.  Shed/overflow is an explicit 503."""
        try:
            doc = json.loads(body.decode("utf-8"))
            rows = doc["inputs"] if "inputs" in doc else [doc["input"]]
            xs = [np.asarray(r, np.float32) for r in rows]
        except (ValueError, KeyError, TypeError) as e:
            return (400, json.dumps(
                {"error": f"bad request: {e!r}"}).encode("utf-8"),
                "application/json")
        reqs = [self.submit(x) for x in xs]
        deadline = time.monotonic() + self.request_timeout_s
        for r in reqs:
            if not r.event.wait(max(0.0, deadline - time.monotonic())):
                if not self._finish_timeout(r):
                    # a worker claimed it mid-forward: the result is
                    # imminent — wait it out rather than race the
                    # ok-accounting with a fabricated timeout
                    r.event.wait(self.request_timeout_s)
        if any(r.error == "shed" for r in reqs):
            return (503, json.dumps(
                {"error": "shed", "shed": sum(1 for r in reqs
                                              if r.error == "shed")}
            ).encode("utf-8"), "application/json")
        if any(r.error or r.result is None for r in reqs):
            return (500, json.dumps(
                {"error": next((r.error or "timeout") for r in reqs
                               if r.error or r.result is None)}
            ).encode("utf-8"), "application/json")
        out = {"outputs": [np.asarray(r.result).tolist() for r in reqs],
               "version": self.replica.version,
               "round": self.replica.last_round(),
               "batch_sizes": [r.batch_size for r in reqs]}
        return (200, json.dumps(out).encode("utf-8"), "application/json")

    def serve_http(self, bind_host: str = "127.0.0.1", port: int = 0):
        """Start the gateway's HTTP surface on the shared exporter:
        ``POST /infer`` plus the standard ``GET`` routes (/metrics,
        /healthz with the serving block, /ledger with the request
        section).  Returns the server (caller owns shutdown)."""
        from geomx_tpu.serve import serving_surface
        from geomx_tpu.telemetry.export import start_http_exporter

        def health():
            out = {"status": "ok"}
            s = serving_surface()
            if s is not None:
                out["serving"] = s
            return out

        return start_http_exporter(
            bind_host, int(port), health_fn=health,
            post_routes={"/infer": self.infer_route},
            thread_name="serve-http")
