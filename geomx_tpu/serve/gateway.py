"""Batched inference gateway: POST /infer -> bounded queue -> jit'd forward.

The gateway is the serving plane's front door (docs/serving.md
"Inference gateway").  Requests arrive over the shared HTTP exporter
(:func:`~geomx_tpu.telemetry.export.start_http_exporter` — the same
plumbing behind the scheduler's ``/metrics``/``/healthz``) or the
native binary ``/infer`` lane (serve/infer_wire.py), coalesce into one
bounded queue, and a continuous-batching worker drains them:

- **coalescing, deadline-or-full**: the worker takes the first waiting
  request, then absorbs arrivals until the batch FILLS or ``queue_ms``
  expires — a full batch closes the instant it fills, it never sleeps
  out the window;
- **pipelined double-buffered dispatch** (the GEOMX_PREFETCH pattern):
  while batch *t* runs on device behind jax's async dispatch, the
  worker is already draining and assembling batch *t+1* into a
  persistent pre-allocated padded bucket buffer (one copy per request,
  no per-batch ``np.stack`` allocation) — host assembly and device
  compute overlap instead of serializing;
- **pre-warmed buckets**: :meth:`warmup` (run by :meth:`start` when
  input shapes are known) compiles every (bucket, input-shape)
  executable up front, so first-request compilation never lands inside
  a served request's latency — counted in the
  ``geomx_serve_warmup_compiles`` gauge, jit cache still bounded;
- **padded buckets, bounded jit cache**: a batch pads up to the next
  power-of-two bucket ≤ ``max_batch``, so the jit cache holds at most
  ``len(buckets)`` executables per input shape — request count can be
  anything, compile count cannot (the pin in tests/test_serve.py);
- **atomic weights**: the forward reads
  :meth:`~geomx_tpu.serve.replica.ServingReplica.params` once per
  batch — the replica's swap discipline means a mid-batch delta
  refresh changes the NEXT batch's weights, never this one's;
- **deterministic shedding**: the SLO policy's ``set_shed_fraction``
  sheds by fractional accumulator (every shed is an explicit 503 the
  client sees and the ``geomx_serve_requests_total{status="shed"}``
  counter records — a shed request is refused, never lost);
- **causal request ledger**: every request lands in the process-global
  :class:`~geomx_tpu.telemetry.ledger.RequestLedger` with its
  enqueue -> batch -> forward -> reply phase seconds and transport
  lane, the p50/p99 surface ``GET /ledger`` serves.

All latency/deadline arithmetic runs on ``time.monotonic()`` — a wall
clock step mid-run must not corrupt p50/p99 or the request deadline;
wall clock survives only as the ledger record's enqueue anchor.

jax is imported lazily inside the forward path only — constructing a
gateway (or importing this module) in a jax-free process is safe.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from geomx_tpu.serve import register_serving_surface
from geomx_tpu.serve.replica import ServingReplica

BATCH_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


# ---------------------------------------------------------------------------
# params pytree <-> named-layer dict (the registry's schema).  The
# leaf index prefixes (zero-padded to 4 digits, wider when the tree
# needs it) make dict insertion order == pytree leaf order == the P3
# "early layers first" refresh priority; ``unflatten_params`` rebuilds
# by parsing the integer prefix — NOT a lexicographic name sort, which
# would put "10000..." before "9999..." and silently reorder leaves.
# ---------------------------------------------------------------------------

def _leaf_index(name: str) -> int:
    """The integer leaf index a :func:`flatten_params` name starts with."""
    i = 0
    while i < len(name) and name[i].isdigit():
        i += 1
    if i == 0:
        raise ValueError(f"param name {name!r} has no leaf-index prefix")
    return int(name[:i])


def flatten_params(tree) -> Tuple[Dict[str, np.ndarray], Any]:
    """A jax pytree as ``({name: np.float32 array}, treedef)``."""
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    named: Dict[str, np.ndarray] = {}
    for i, (path, leaf) in enumerate(flat):
        name = f"{i:04d}{jax.tree_util.keystr(path)}"
        named[name] = np.asarray(leaf, np.float32)
    return named, treedef


def unflatten_params(treedef, named: Dict[str, np.ndarray]):
    """Inverse of :func:`flatten_params` (names sort by their integer
    leaf-index prefix; the sequence must be contiguous from 0)."""
    import jax
    keys = sorted(named, key=_leaf_index)
    if [_leaf_index(k) for k in keys] != list(range(len(keys))):
        raise ValueError(
            "named params do not form a contiguous 0..n-1 leaf-index "
            "sequence — refusing to rebuild a reordered pytree")
    return jax.tree_util.tree_unflatten(
        treedef, [named[k] for k in keys])


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to (and including) ``max_batch``."""
    out = []
    b = 1
    while b < int(max_batch):
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return tuple(sorted(set(out)))


class _Request:
    __slots__ = ("x", "event", "result", "error", "rid", "t_enqueue",
                 "t_enqueue_unix", "t_batch", "batch_size", "bucket",
                 "transport", "model_version", "model_round",
                 "staleness_s", "_taken_lock", "_taken")

    def __init__(self, x: np.ndarray, rid: int,
                 transport: str = "local"):
        self.x = x
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[str] = None
        self.rid = rid
        # monotonic for every latency/deadline computation; wall clock
        # kept ONLY as the ledger record's anchor
        self.t_enqueue = time.monotonic()
        self.t_enqueue_unix = time.time()
        self.t_batch: Optional[float] = None
        self.batch_size = 0
        self.bucket = 0
        self.transport = transport
        # freshness provenance, stamped at dispatch from the weight set
        # the batch actually ran on (None until then / on non-ok ends)
        self.model_version: Optional[str] = None
        self.model_round: Optional[int] = None
        self.staleness_s: Optional[float] = None
        self._taken_lock = threading.Lock()
        self._taken = False

    def take(self) -> bool:
        """Claim terminal ownership — exactly one caller wins.  The
        batch worker takes before dispatching; the HTTP thread takes on
        client-deadline expiry — so a request that timed out while
        queued is skipped by a later batch (and counted "timeout"),
        never double-finished or counted "ok" after its 500."""
        with self._taken_lock:
            if self._taken:
                return False
            self._taken = True
            return True


class InferenceGateway:
    """Continuous-batching inference over one serving replica."""

    def __init__(self, replica: ServingReplica, treedef,
                 model_name: str = "mlp", num_classes: int = 10,
                 max_batch: int = 8, queue_ms: float = 2.0,
                 queue_cap: int = 256,
                 buckets: Optional[Tuple[int, ...]] = None,
                 apply_fn: Optional[Callable] = None,
                 request_timeout_s: Optional[float] = None,
                 warmup_shapes: Optional[List[tuple]] = None,
                 warmup: Optional[bool] = None):
        self.replica = replica
        self.treedef = treedef
        self.model_name = str(model_name)
        self.num_classes = int(num_classes)
        self.max_batch = max(1, int(max_batch))
        self.queue_ms = max(0.0, float(queue_ms))
        self.buckets = tuple(sorted(buckets)) if buckets \
            else default_buckets(self.max_batch)
        if self.buckets[-1] < self.max_batch:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} < max_batch "
                f"{self.max_batch}: a full batch would have no bucket")
        if request_timeout_s is None or warmup is None:
            from geomx_tpu.config import GeoConfig
            cfg = GeoConfig.from_env()
            if request_timeout_s is None:
                request_timeout_s = cfg.serve_timeout_s
            if warmup is None:
                warmup = cfg.serve_warmup
        self.request_timeout_s = max(0.001, float(request_timeout_s))
        self.warmup_shapes = [tuple(int(d) for d in s)
                              for s in (warmup_shapes or [])]
        self._warmup_enabled = bool(warmup)
        self._apply_fn = apply_fn          # overrides get_model (tests)
        self._model = None
        self._queue: "queue.Queue[Optional[_Request]]" = \
            queue.Queue(maxsize=max(1, int(queue_cap)))
        self._jit_cache: Dict[tuple, Any] = {}
        # persistent padded host buffers, two per (bucket, feat shape)
        # key: the worker assembles batch t+1 into the OTHER buffer
        # while batch t's host->device transfer may still be reading
        # its own — ping-pong, never a per-batch np.stack allocation
        self._host_bufs: Dict[tuple, List[np.ndarray]] = {}
        self._buf_flip: Dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._rid = 0
        self._shed_fraction = 0.0
        self._shed_acc = 0.0
        self._running = False
        self._worker: Optional[threading.Thread] = None
        self.requests_ok = 0
        self.requests_shed = 0
        self.requests_error = 0
        self.requests_timeout = 0
        self.batches_dispatched = 0
        self.warmup_compiles = 0

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> "InferenceGateway":
        if self._warmup_enabled and self.warmup_shapes:
            # compile BEFORE the worker serves: the r01 p99/p50 gap was
            # first-request bucket compiles landing inside request
            # latency
            self.warmup()
        self._running = True
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="serve-batcher", daemon=True)
        self._worker.start()
        register_serving_surface("gateway", self.surface_snapshot)
        return self

    def warmup(self, input_shapes: Optional[List[tuple]] = None) -> int:
        """Compile (and execute once, on zeros) every (bucket, input
        shape) executable so no served request ever pays a compile.
        Returns the number of NEW executables compiled; the cumulative
        count exports as the ``geomx_serve_warmup_compiles`` gauge.
        The cache bound is untouched — warmup populates exactly the
        same bounded (bucket, shape) key set the serving path would."""
        shapes = [tuple(int(d) for d in s)
                  for s in (input_shapes
                            if input_shapes is not None
                            else self.warmup_shapes)]
        named = self.replica.params()
        if not shapes or not named:
            return 0
        compiles = 0
        for shape in shapes:
            for b in self.buckets:
                key = (int(b),) + shape
                fresh = key not in self._jit_cache
                fn = self._forward_fn(b, shape)
                xb = np.zeros((int(b),) + shape, np.float32)
                np.asarray(fn(named, xb))   # block: the compile (and
                #                             first run) happens HERE
                if fresh:
                    compiles += 1
        self.warmup_compiles += compiles
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().gauge(
                "geomx_serve_warmup_compiles",
                "Bucket executables compiled up front by gateway "
                "warmup").set(float(self.warmup_compiles))
        except Exception:
            pass
        return compiles

    def stop(self) -> None:
        self._running = False
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        if self._worker is not None:
            self._worker.join(timeout=10.0)
        register_serving_surface("gateway", None)

    # ---- SLO hooks (control/policy.py SloPolicy actuates these) ------------

    def set_shed_fraction(self, fraction: float) -> None:
        with self._lock:
            self._shed_fraction = min(1.0, max(0.0, float(fraction)))

    def shed_fraction(self) -> float:
        with self._lock:
            return self._shed_fraction

    def serving_stats(self) -> dict:
        """The observation the SLO policy consumes: request-ledger
        percentiles + live queue depth + the current shed fraction."""
        from geomx_tpu.telemetry.ledger import get_request_ledger
        s = get_request_ledger().summary()
        return {"p50_s": s.get("total_p50_s"),
                "p99_s": s.get("total_p99_s"),
                "qps": s.get("qps"),
                "queue_depth": self._queue.qsize(),
                "shed_fraction": self.shed_fraction()}

    # ---- submission --------------------------------------------------------

    def submit(self, x: np.ndarray,
               transport: str = "local") -> _Request:
        """Enqueue one example.  A full queue or an active shed marks
        the request shed immediately (explicit refusal, never silent
        loss).  ``transport`` labels the request's ledger record with
        the lane it arrived on (``http`` / ``native`` / ``local``)."""
        with self._lock:
            self._rid += 1
            rid = self._rid
            shed = False
            if self._shed_fraction > 0.0:
                self._shed_acc += self._shed_fraction
                if self._shed_acc >= 1.0:
                    self._shed_acc -= 1.0
                    shed = True
        req = _Request(np.asarray(x, np.float32), rid,
                       transport=transport)
        if shed:
            self._finish_shed(req)
            return req
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self._finish_shed(req)
            return req
        self._observe_queue_depth()
        return req

    def _finish_shed(self, req: _Request) -> None:
        req.take()          # fresh request, unqueued: always wins
        req.error = "shed"
        req.event.set()
        # every ThreadingHTTPServer thread calls submit concurrently —
        # the counter bump must sit under the gateway lock or the
        # read-modify-write race loses sheds from the zero-lost books
        with self._lock:
            self.requests_shed += 1
        self._count_request("shed")
        self._ledger_observe(req, status="shed", forward_s=0.0,
                             reply_s=0.0)

    def _finish_timeout(self, req: _Request) -> bool:
        """Finish a request whose client deadline expired while it was
        still queued.  False = a batch worker already claimed it (the
        forward is in flight and the result/event are imminent)."""
        if not req.take():
            return False
        req.error = "timeout"
        req.event.set()
        with self._lock:
            self.requests_timeout += 1
        self._count_request("timeout")
        self._ledger_observe(req, status="timeout", forward_s=0.0,
                             reply_s=0.0)
        return True

    # ---- the pipelined continuous-batching worker --------------------------

    def _worker_loop(self) -> None:
        """Double-buffered dispatch (the GEOMX_PREFETCH pattern): jax
        dispatch is asynchronous, so ``_dispatch_async`` returns while
        batch *t* still runs on device; the worker immediately drains
        and assembles batch *t+1*, and only then blocks on *t*'s result
        in ``_finalize`` — host batch assembly hides behind device
        compute.  With nothing queued, an in-flight batch finalizes
        immediately (no latency tax at light load)."""
        pending = None      # (batch, out_device, t_f0) in flight
        stopping = False
        while self._running and not stopping:
            if pending is None:
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
            else:
                try:
                    first = self._queue.get_nowait()
                except queue.Empty:
                    self._finalize(*pending)
                    pending = None
                    continue
            if first is None:
                break
            batch = [first]
            # deadline-or-full coalescing: a full batch closes the
            # moment it fills; while a batch is already in flight the
            # device is the clock — absorb whatever is queued right
            # now without sleeping out the window
            deadline = time.monotonic() + self.queue_ms / 1000.0
            while len(batch) < self.max_batch:
                try:
                    if pending is not None:
                        nxt = self._queue.get_nowait()
                    else:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    stopping = True
                    break
                batch.append(nxt)
            new_pending = self._dispatch_async(batch)
            if pending is not None:
                self._finalize(*pending)
            pending = new_pending
        if pending is not None:
            self._finalize(*pending)
        # drain on stop: whatever is queued still gets an answer
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is not None:
                done = self._dispatch_async([req])
                if done is not None:
                    self._finalize(*done)

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def jit_cache_size(self) -> int:
        return len(self._jit_cache)

    def _forward_fn(self, bucket: int, feat_shape: tuple):
        """The jit'd forward for one padded bucket size (bounded cache:
        one executable per (bucket, input feature shape)).  Off-CPU the
        padded input buffer is donated — the gateway's ping-pong host
        buffers never read a dispatched batch back, so the device copy
        is dead weight the executable may reuse; on CPU donation is
        skipped (unusable there, and jax warns per call)."""
        key = (int(bucket),) + tuple(feat_shape)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        import jax
        if self._apply_fn is not None:
            # injected forward takes the flat named dict directly (tests
            # and jax-light callers skip the treedef round-trip)
            apply = self._apply_fn

            def fwd(named_params, xb):
                return apply(named_params, xb)
        else:
            if self._model is None:
                from geomx_tpu.models import get_model
                self._model = get_model(self.model_name,
                                        num_classes=self.num_classes)
            model = self._model

            def fwd(named_params, xb):
                variables = unflatten_params(self.treedef, named_params)
                return model.apply(variables, xb, train=False)

        if jax.default_backend() != "cpu":
            fn = jax.jit(fwd, donate_argnums=(1,))
        else:
            fn = jax.jit(fwd)
        self._jit_cache[key] = fn
        return fn

    def _assemble(self, bucket: int, batch: List[_Request],
                  feat_shape: tuple) -> np.ndarray:
        """Copy the batch into a persistent pre-allocated padded bucket
        buffer: one row copy per request, pad rows zeroed — never a
        per-batch ``np.stack`` + ``np.concatenate`` allocation pair.
        Buffers ping-pong per (bucket, shape): the previous batch's
        host->device transfer may still be in flight on its buffer
        while this one fills the other."""
        key = (int(bucket),) + tuple(feat_shape)
        bufs = self._host_bufs.get(key)
        if bufs is None:
            bufs = [np.zeros((int(bucket),) + tuple(feat_shape),
                             np.float32) for _ in range(2)]
            self._host_bufs[key] = bufs
            self._buf_flip[key] = 0
        flip = self._buf_flip[key] ^ 1
        self._buf_flip[key] = flip
        buf = bufs[flip]
        n = len(batch)
        for i, r in enumerate(batch):
            buf[i] = r.x        # raises on a shape mismatch -> error
            #                     fan-out upstream, same as np.stack did
        if n < bucket:
            buf[n:] = 0.0
        return buf

    def _dispatch_async(self, batch: List[_Request]):
        """Claim + assemble + dispatch one batch; returns the in-flight
        ``(batch, out_device, t_f0)`` triple for ``_finalize`` — jax
        async dispatch means the device result is a future, not a
        value.  None = nothing survived claiming or the dispatch itself
        failed (already error-finished)."""
        # claim each request first: one that timed out while queued was
        # already finished (500 + "timeout" accounting) by the HTTP
        # thread — running it anyway would count it "ok" after the
        # client gave up
        batch = [r for r in batch if r.take()]
        if not batch:
            self._observe_queue_depth()
            return None
        t_batch = time.monotonic()
        n = len(batch)
        bucket = self.bucket_for(n)
        for r in batch:
            r.t_batch = t_batch
            r.batch_size = n
            r.bucket = bucket
        try:
            feat_shape = tuple(np.shape(batch[0].x))
            xb = self._assemble(bucket, batch, feat_shape)
            named = self.replica.params()
            # freshness provenance: the version/round/staleness of the
            # weight set THIS batch runs on, stamped next to the params
            # read so reply and ledger describe the weights actually
            # used, not whatever the replica holds at reply time
            ver = self.replica.version
            rnd = self.replica.last_round()
            stale = self.replica.staleness_s()
            for r in batch:
                r.model_version = ver
                r.model_round = rnd
                r.staleness_s = None if stale == float("inf") \
                    else float(stale)
            fn = self._forward_fn(bucket, feat_shape)
            t_f0 = time.monotonic()
            return (batch, fn(named, xb), t_f0)
        except Exception as e:
            self._finish_error(batch, e)
            return None

    def _finalize(self, batch: List[_Request], out_dev, t_f0) -> None:
        """Block on an in-flight batch's device result and fan out the
        replies + terminal accounting."""
        try:
            out = np.asarray(out_dev)       # the block point
            forward_s = time.monotonic() - t_f0
            self.batches_dispatched += 1
            self._observe_batch(len(batch))
            t_reply0 = time.monotonic()
            for i, r in enumerate(batch):
                r.result = out[i]
                r.event.set()
            reply_s = time.monotonic() - t_reply0
            for r in batch:
                self.requests_ok += 1
                self._count_request("ok")
                self._ledger_observe(r, status="ok",
                                     forward_s=forward_s,
                                     reply_s=reply_s)
            # propagation join's terminal hop: this batch served its
            # round, per transport (the tracker keeps only the first)
            try:
                from geomx_tpu.telemetry.fleetscope import \
                    note_propagation
                for r in batch:
                    if r.model_round:
                        note_propagation(r.model_round, "served",
                                         transport=r.transport)
            except Exception:
                pass
        except Exception as e:
            self._finish_error(batch, e)
        self._observe_queue_depth()
        self._observe_staleness()

    def _finish_error(self, batch: List[_Request], e: Exception) -> None:
        for r in batch:
            r.error = repr(e)
            r.event.set()
            self.requests_error += 1
            self._count_request("error")
            self._ledger_observe(r, status="error", forward_s=0.0,
                                 reply_s=0.0)
        self._observe_queue_depth()

    # ---- telemetry ---------------------------------------------------------

    def _count_request(self, status: str) -> None:
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().counter(
                "geomx_serve_requests_total",
                "Inference requests by terminal status",
                ("status",)).labels(status=status).inc()
        except Exception:
            pass

    def _observe_batch(self, n: int) -> None:
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().histogram(
                "geomx_serve_batch_size",
                "Dispatched inference batch sizes (pre-padding)",
                buckets=BATCH_SIZE_BUCKETS).observe(float(n))
        except Exception:
            pass

    def _observe_queue_depth(self) -> None:
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().gauge(
                "geomx_serve_queue_depth",
                "Inference requests waiting in the gateway queue"
            ).set(float(self._queue.qsize()))
        except Exception:
            pass

    def _observe_staleness(self) -> None:
        try:
            from geomx_tpu.telemetry.registry import get_registry
            s = self.replica.staleness_s()
            if s != float("inf"):
                get_registry().gauge(
                    "geomx_serve_replica_staleness_seconds",
                    "Seconds since the serving replica's last "
                    "successful weight refresh").set(float(s))
        except Exception:
            pass

    def _ledger_observe(self, req: _Request, status: str,
                        forward_s: float, reply_s: float) -> None:
        try:
            from geomx_tpu.telemetry.ledger import get_request_ledger
            t_batch = req.t_batch if req.t_batch is not None \
                else req.t_enqueue
            # queue_s from the monotonic pair; the record's anchor
            # stays wall clock (the one place wall time belongs)
            get_request_ledger().observe(
                rid=req.rid, t_enqueue=req.t_enqueue_unix,
                queue_s=max(0.0, t_batch - req.t_enqueue),
                forward_s=forward_s, reply_s=reply_s,
                batch_size=req.batch_size, bucket=req.bucket,
                status=status, transport=req.transport,
                model_version=req.model_version,
                model_round=req.model_round,
                staleness_s=req.staleness_s)
        except Exception:
            pass

    # ---- surfaces ----------------------------------------------------------

    def wait_requests(self, reqs: List[_Request]) -> None:
        """Wait a submitted group out under ONE shared client deadline
        (both the HTTP door and the native lane use this): a request
        still unanswered at the deadline is claimed as a timeout —
        unless a batch worker claimed it first, in which case the
        result is imminent and fabricating a timeout would race the
        ok-accounting."""
        deadline = time.monotonic() + self.request_timeout_s
        for r in reqs:
            if not r.event.wait(max(0.0, deadline - time.monotonic())):
                if not self._finish_timeout(r):
                    r.event.wait(self.request_timeout_s)

    def surface_snapshot(self) -> dict:
        """The ``/healthz`` serving block: published versions the
        replica tracks, freshness, queue depth, terminal counts."""
        return {"replica": self.replica.snapshot(),
                "queue_depth": self._queue.qsize(),
                "max_batch": self.max_batch,
                "queue_ms": self.queue_ms,
                "request_timeout_s": self.request_timeout_s,
                "buckets": list(self.buckets),
                "jit_cache_size": self.jit_cache_size(),
                "warmup_compiles": self.warmup_compiles,
                "shed_fraction": self.shed_fraction(),
                "requests": {"ok": self.requests_ok,
                             "shed": self.requests_shed,
                             "error": self.requests_error,
                             "timeout": self.requests_timeout},
                "batches": self.batches_dispatched}

    def infer_route(self, body: bytes) -> Tuple[int, bytes, str]:
        """The ``POST /infer`` handler (wire shape in docs/serving.md):
        ``{"inputs": [[...feature vector...], ...]}`` in, one output
        row per input out.  Shed/overflow is an explicit 503."""
        try:
            doc = json.loads(body.decode("utf-8"))
            rows = doc["inputs"] if "inputs" in doc else [doc["input"]]
            xs = [np.asarray(r, np.float32) for r in rows]
        except (ValueError, KeyError, TypeError) as e:
            return (400, json.dumps(
                {"error": f"bad request: {e!r}"}).encode("utf-8"),
                "application/json")
        self._account_wire("http", "rx", len(body))
        reqs = [self.submit(x, transport="http") for x in xs]
        self.wait_requests(reqs)
        if any(r.error == "shed" for r in reqs):
            return (503, json.dumps(
                {"error": "shed", "shed": sum(1 for r in reqs
                                              if r.error == "shed")}
            ).encode("utf-8"), "application/json")
        if any(r.error or r.result is None for r in reqs):
            return (500, json.dumps(
                {"error": next((r.error or "timeout") for r in reqs
                               if r.error or r.result is None)}
            ).encode("utf-8"), "application/json")
        stale = self.replica.staleness_s()
        out = {"outputs": [np.asarray(r.result).tolist() for r in reqs],
               "version": self.replica.version,
               "round": self.replica.last_round(),
               "batch_sizes": [r.batch_size for r in reqs],
               # freshness provenance (additive — old clients that only
               # read outputs/version/round are untouched)
               "staleness_s": (None if stale == float("inf")
                               else round(float(stale), 3)),
               "layer_rounds": self.replica.layer_rounds()}
        payload = json.dumps(out).encode("utf-8")
        self._account_wire("http", "tx", len(payload))
        return (200, payload, "application/json")

    def _account_wire(self, transport: str, direction: str,
                      nbytes: int, declared=None) -> None:
        try:
            from geomx_tpu.telemetry.ledger import get_request_ledger
            get_request_ledger().account_wire(transport, direction,
                                              nbytes, declared=declared)
        except Exception:
            pass

    def serve_http(self, bind_host: str = "127.0.0.1", port: int = 0):
        """Start the gateway's HTTP surface on the shared exporter:
        ``POST /infer`` plus the standard ``GET`` routes (/metrics,
        /healthz with the serving block, /ledger with the request
        section).  Returns the server (caller owns shutdown)."""
        from geomx_tpu.serve import serving_surface
        from geomx_tpu.telemetry.export import start_http_exporter

        def health():
            out = {"status": "ok"}
            s = serving_surface()
            if s is not None:
                out["serving"] = s
            return out

        return start_http_exporter(
            bind_host, int(port), health_fn=health,
            post_routes={"/infer": self.infer_route},
            thread_name="serve-http")

    def register_with_scheduler(self, scheduler_addr, http_port: int,
                                host: str = "127.0.0.1",
                                tag: str = "gateway",
                                heartbeat_interval_s: Optional[float]
                                = None):
        """Join the scheduler roster as node kind ``"serve"`` (the
        registered port IS the node's HTTP surface, so FleetScope
        discovery needs no side-channel config) and start the standard
        heartbeat — a dead gateway becomes a *named* death in the
        scheduler's ``/healthz`` instead of silently missing traffic.
        Returns the :class:`SchedulerClient`; the caller owns
        ``close()``."""
        from geomx_tpu.service.scheduler import SchedulerClient
        client = SchedulerClient((str(scheduler_addr[0]),
                                  int(scheduler_addr[1])))
        client.register("serve", host=host, port=int(http_port),
                        tag=str(tag))
        client.start_heartbeat(heartbeat_interval_s)
        return client
