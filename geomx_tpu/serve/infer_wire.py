"""Native binary inference lane: persistent sockets, zero-copy frames.

The serving fast path's front door for high-QPS clients (docs/
serving.md "Serving fast path").  The HTTP ``POST /infer`` door pays a
TCP connect + JSON encode/decode per request; this lane speaks the
service plane's v0x02 zero-copy TLV wire (service/protocol.py) over
ONE persistent connection per client:

- request: ``Msg(INFER, key="infer", meta={"rid", "wire_declared"},
  array=float32[rows, feat])`` — the payload crosses as raw fp32 and
  decodes as a zero-copy ``np.frombuffer`` view straight into the
  gateway's queue (the batch assembler's row copy is the only copy);
- reply: ``Msg(INFER_REPLY, meta={"rid", "version", "round",
  "batch_sizes", "staleness_s", "layer_rounds", "wire_declared"},
  array=float32[rows, out])`` — the freshness-provenance keys are
  additive (old clients ignore them: mixed-fleet safe) — or an
  error meta (``shed`` / ``timeout`` / the exception repr) instead of
  a torn socket, mirroring the registry's ERROR-frame discipline;
- both directions land in the process-global RequestLedger's byte-true
  wire accounting: actual on-wire frame bytes (length prefix included)
  against the sender's ``wire_declared`` payload claim — the same
  honesty audit the gradient plane runs, here bounding inference frame
  overhead (the ≤ 1.02 serving acceptance gate).

Both doors feed the SAME gateway queue and the same continuous-
batching worker — the lane changes transport cost, never semantics:
shedding, timeouts, the request ledger and the SLO policy see one
unified request stream, each record labeled with its transport.

Host-plane Python only (numpy + sockets, no jax at import).
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Optional, Tuple

import numpy as np

from geomx_tpu.serve.gateway import InferenceGateway
from geomx_tpu.service.protocol import (Msg, MsgType, connect_retry,
                                        recv_frame_sized, send_frame)


def _account(direction: str, nbytes: int, declared=None) -> None:
    try:
        from geomx_tpu.telemetry.ledger import get_request_ledger
        get_request_ledger().account_wire("native", direction, nbytes,
                                          declared=declared)
    except Exception:
        pass


class NativeInferenceServer:
    """TCP front for one :class:`InferenceGateway` — the service-plane
    accept/serve/dispatch socket loop (the RegistryServer idiom), one
    daemon thread per persistent client connection."""

    def __init__(self, gateway: InferenceGateway, port: int = 0,
                 bind_host: Optional[str] = None):
        self.gateway = gateway
        if bind_host is None:
            # host-plane bind knob, parity with GeoPSServer/Registry
            # graftlint: disable=GXL006 — host-plane knob
            bind_host = os.environ.get("GEOMX_PS_BIND_HOST", "127.0.0.1")
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        from geomx_tpu.service.server import GeoPSServer
        GeoPSServer._bind_with_retry(self._srv, bind_host, int(port))
        self._srv.listen(64)
        self._srv.settimeout(0.2)
        self.addr = self._srv.getsockname()
        self.port = self.addr[1]
        self._running = True
        self._conns: set = set()
        self.frames_served = 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="infer-accept", daemon=True)

    def start(self) -> "NativeInferenceServer":
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        for sock in [self._srv] + list(self._conns):
            try:
                sock.close()
            except OSError:
                pass

    def join(self, timeout: Optional[float] = None) -> None:
        self._accept_thread.join(timeout)

    # ---- networking --------------------------------------------------------

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while self._running:
                got = recv_frame_sized(conn)
                if got is None:
                    return
                if not self._dispatch(conn, *got):
                    return
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            self._conns.discard(conn)
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn: socket.socket, msg: Msg,
                  nbytes: int) -> bool:
        if msg.type != MsgType.INFER:
            send_frame(conn, Msg(
                MsgType.ERROR, sender=-1,
                meta={"error": f"unhandled {msg.type.name}",
                      "rid": msg.meta.get("rid", 0)}))
            return True
        rid = msg.meta.get("rid", 0)
        _account("rx", nbytes, declared=msg.meta.get("wire_declared"))
        # a malformed batch answers an INFER_REPLY error frame, never a
        # torn socket — the client would otherwise retry the identical
        # frame and see an opaque ConnectionError instead of the cause
        try:
            arr = np.asarray(msg.array, np.float32)
            if arr.ndim == 1:
                arr = arr[None, :]
            if arr.ndim < 2 or arr.shape[0] < 1:
                raise ValueError(f"bad inference batch shape {arr.shape}")
        except (TypeError, ValueError) as e:
            tx = send_frame(conn, Msg(
                MsgType.INFER_REPLY, sender=-1,
                meta={"rid": rid, "error": f"bad request: {e!r}"}))
            _account("tx", tx)
            return True
        gw = self.gateway
        reqs = [gw.submit(arr[i], transport="native")
                for i in range(arr.shape[0])]
        gw.wait_requests(reqs)
        if any(r.error == "shed" for r in reqs):
            tx = send_frame(conn, Msg(
                MsgType.INFER_REPLY, sender=-1,
                meta={"rid": rid, "error": "shed",
                      "shed": sum(1 for r in reqs
                                  if r.error == "shed")}))
            _account("tx", tx)
            return True
        if any(r.error or r.result is None for r in reqs):
            tx = send_frame(conn, Msg(
                MsgType.INFER_REPLY, sender=-1,
                meta={"rid": rid,
                      "error": next((r.error or "timeout") for r in reqs
                                    if r.error or r.result is None)}))
            _account("tx", tx)
            return True
        out = np.ascontiguousarray(
            np.stack([np.asarray(r.result) for r in reqs]), np.float32)
        stale = gw.replica.staleness_s()
        tx = send_frame(conn, Msg(
            MsgType.INFER_REPLY, key="infer", sender=-1,
            # staleness_s + layer_rounds are additive freshness
            # provenance: the v0x02 TLV meta codec ships unknown keys
            # through its generic fallback, so an old client decodes
            # the frame unchanged and simply ignores them (mixed-fleet
            # safe — pinned by test_infer_reply_provenance_wire_safe)
            meta={"rid": rid, "version": gw.replica.version,
                  "round": gw.replica.last_round(),
                  "batch_sizes": [r.batch_size for r in reqs],
                  "staleness_s": (None if stale == float("inf")
                                  else float(stale)),
                  "layer_rounds": gw.replica.layer_rounds(),
                  "wire_declared": int(out.nbytes)},
            array=out))
        _account("tx", tx, declared=int(out.nbytes))
        self.frames_served += 1
        return True


class NativeInferenceClient:
    """One persistent connection to a :class:`NativeInferenceServer`.

    Synchronous request/reply; thread-UNSAFE by design (one client per
    load thread — a lock would serialize exactly the concurrency the
    lane exists to win).  A send that dies mid-flight reconnects once
    and replays: inference is idempotent, so the retry is safe."""

    def __init__(self, addr: Tuple[str, int], timeout_s: float = 30.0):
        self.addr = (str(addr[0]), int(addr[1]))
        self.timeout_s = float(timeout_s)
        self._sock: Optional[socket.socket] = None
        self._rid = 0

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = connect_retry(self.addr,
                                       total_timeout_s=self.timeout_s)
            self._sock.settimeout(self.timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def infer(self, x: np.ndarray, retries: int = 1) -> dict:
        """One inference batch (``[rows, feat]`` float32; a single row
        is auto-batched).  Returns ``{"outputs": float32[rows, out],
        "version", "round", "batch_sizes", "staleness_s",
        "layer_rounds"}``, or ``{"error": ...}``
        (plus ``"shed"`` count when shed) — explicit refusal, never a
        dropped request."""
        arr = np.ascontiguousarray(x, np.float32)
        if arr.ndim == 1:
            arr = arr[None, :]
        self._rid += 1
        msg = Msg(MsgType.INFER, key="infer", sender=0,
                  meta={"rid": self._rid,
                        "wire_declared": int(arr.nbytes)},
                  array=arr)
        for attempt in range(retries + 1):
            try:
                sock = self._conn()
                send_frame(sock, msg)
                got = recv_frame_sized(sock)
                if got is None:
                    raise ConnectionError("infer lane closed mid-reply")
                rep, _ = got
                break
            except (ConnectionError, OSError, TimeoutError):
                self.close()
                if attempt >= retries:
                    raise
        if rep.type == MsgType.ERROR:
            return {"error": rep.meta.get("error", "server error")}
        out = dict(rep.meta)
        if rep.array is not None:
            out["outputs"] = np.asarray(rep.array, np.float32)
        return out


def serve_native(gateway: InferenceGateway, port: int = 0,
                 bind_host: Optional[str] = None
                 ) -> Optional[NativeInferenceServer]:
    """Start the native lane next to the HTTP door, honoring the
    ``GEOMX_SERVE_NATIVE_WIRE`` knob (None when disabled).  The caller
    owns ``stop()``, mirroring :meth:`InferenceGateway.serve_http`."""
    from geomx_tpu.config import GeoConfig
    if not GeoConfig.from_env().serve_native_wire:
        return None
    return NativeInferenceServer(gateway, port=port,
                                 bind_host=bind_host).start()
