"""Evaluation metrics — the ``mx.metric`` surface.

Reference: python/mxnet/metric.py — EvalMetric base (update/get/reset,
name-value pairs), the standard classification/regression metrics, a
composite container, and a ``create`` factory.  These run on host numpy:
metrics consume already-device_get results, keeping the jitted step free
of data-dependent work.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np


def _to_np(x) -> np.ndarray:
    return np.asarray(x)


class EvalMetric:
    """Base metric: running (sum, count) with update/get/reset
    (reference python/mxnet/metric.py EvalMetric)."""

    def __init__(self, name: str):
        self.name = name
        self.reset()

    def reset(self) -> None:
        self.sum_metric = 0.0
        self.num_inst = 0

    def update(self, labels, preds) -> None:
        raise NotImplementedError

    def get(self) -> Tuple[str, float]:
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        return [(name, value)]


class Accuracy(EvalMetric):
    def __init__(self, name: str = "accuracy"):
        super().__init__(name)

    def update(self, labels, preds) -> None:
        labels, preds = _to_np(labels), _to_np(preds)
        if preds.ndim == labels.ndim + 1:
            preds = np.argmax(preds, axis=-1)
        self.sum_metric += float((preds.astype(np.int64) ==
                                  labels.astype(np.int64)).sum())
        self.num_inst += labels.size


class TopKAccuracy(EvalMetric):
    def __init__(self, top_k: int = 1, name: Optional[str] = None):
        self.top_k = int(top_k)
        super().__init__(name or f"top_k_accuracy_{top_k}")

    def update(self, labels, preds) -> None:
        labels, preds = _to_np(labels), _to_np(preds)
        topk = np.argsort(preds, axis=-1)[..., -self.top_k:]
        hit = (topk == labels[..., None]).any(axis=-1)
        self.sum_metric += float(hit.sum())
        self.num_inst += labels.size


class F1(EvalMetric):
    """Binary F1 over {0,1} labels; predictions are class scores or
    hard labels (reference metric.py F1)."""

    def __init__(self, name: str = "f1"):
        super().__init__(name)

    def reset(self) -> None:
        super().reset()
        self.tp = self.fp = self.fn = 0

    def update(self, labels, preds) -> None:
        labels, preds = _to_np(labels), _to_np(preds)
        if preds.ndim == labels.ndim + 1:
            preds = np.argmax(preds, axis=-1)
        preds = preds.astype(np.int64)
        labels = labels.astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())
        self.num_inst = 1  # get() reports the ratio directly

    def get(self) -> Tuple[str, float]:
        prec = self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0
        rec = self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        return self.name, f1


class MAE(EvalMetric):
    def __init__(self, name: str = "mae"):
        super().__init__(name)

    def update(self, labels, preds) -> None:
        labels, preds = _to_np(labels), _to_np(preds)
        self.sum_metric += float(np.abs(labels - preds).sum())
        self.num_inst += labels.size


class MSE(EvalMetric):
    def __init__(self, name: str = "mse"):
        super().__init__(name)

    def update(self, labels, preds) -> None:
        labels, preds = _to_np(labels), _to_np(preds)
        self.sum_metric += float(((labels - preds) ** 2).sum())
        self.num_inst += labels.size


class RMSE(MSE):
    def __init__(self, name: str = "rmse"):
        super().__init__(name)

    def get(self) -> Tuple[str, float]:
        name, mse = super().get()
        return name, float(np.sqrt(mse))


class CrossEntropy(EvalMetric):
    """Mean negative log-likelihood of the true class; preds are
    probabilities [..., num_classes] (reference metric.py CrossEntropy)."""

    def __init__(self, eps: float = 1e-12, name: str = "cross-entropy"):
        self.eps = eps
        super().__init__(name)

    def update(self, labels, preds) -> None:
        labels, preds = _to_np(labels), _to_np(preds)
        labels = labels.astype(np.int64).reshape(-1)
        p = preds.reshape(len(labels), -1)[np.arange(len(labels)), labels]
        self.sum_metric += float(-np.log(np.maximum(p, self.eps)).sum())
        self.num_inst += len(labels)


class CompositeEvalMetric(EvalMetric):
    """Bundle of metrics updated together (reference CompositeEvalMetric)."""

    def __init__(self, metrics: Optional[Sequence[EvalMetric]] = None,
                 name: str = "composite"):
        self.metrics: List[EvalMetric] = list(metrics or [])
        super().__init__(name)

    def add(self, metric: "EvalMetric") -> None:
        self.metrics.append(metric)

    def reset(self) -> None:
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds) -> None:
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values

    def get_name_value(self):
        return [m.get() for m in self.metrics]


_REGISTRY = {
    "acc": Accuracy, "accuracy": Accuracy,
    "top_k_accuracy": TopKAccuracy, "top_k_acc": TopKAccuracy,
    "f1": F1,
    "mae": MAE, "mse": MSE, "rmse": RMSE,
    "ce": CrossEntropy, "cross-entropy": CrossEntropy,
}


def create(metric: Union[str, Callable, Sequence], **kwargs) -> EvalMetric:
    """Factory mirroring mx.metric.create: a name, a list of names (->
    composite), or an EvalMetric instance passes through."""
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        return CompositeEvalMetric([create(m) for m in metric], **kwargs)
    name = str(metric).lower()
    if name not in _REGISTRY:
        raise ValueError(f"Unknown metric {metric!r}; "
                         f"options: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)
