"""ResNet family for the flagship CIFAR10 benchmark (BASELINE.md).

CIFAR-style ResNet-20/32/56 (He et al. 2016, section 4.2: 3 stages of n
basic blocks at 16/32/64 channels, 3x3 stem) and an ImageNet-style
ResNet-18 variant.  bfloat16 compute with fp32 parameters/statistics is
the TPU-native mixed-precision recipe: matmuls/convs hit the MXU at
bf16 throughput while the optimizer and BatchNorm stay fp32.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.float32
    norm: ModuleDef = nn.BatchNorm
    # MXU-friendly transition shortcut (VERDICT r4 weak #3): the
    # reference's stride-2 1x1 projection contracts over only cin
    # channels (16 or 32 — an MXU fill of 0.04-0.10 measured in the r4
    # per-op profile) AND discards 3/4 of the activations before
    # projecting.  space_to_depth(2) + unstrided 1x1 is the same output
    # shape with a 4*cin contraction (4x the systolic fill) and uses
    # every input position — the lossless sibling of ResNet-D's
    # avgpool+1x1 downsample.  Flag-gated; default keeps the reference
    # projection exactly.
    mxu_shortcut: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype,
                    kernel_init=nn.initializers.he_normal())(x)
        y = self.norm(use_running_average=not train, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype,
                    kernel_init=nn.initializers.he_normal())(y)
        y = self.norm(use_running_average=not train, dtype=self.dtype,
                      scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            if self.mxu_shortcut and self.strides == 2 \
                    and residual.shape[1] % 2 == 0 \
                    and residual.shape[2] % 2 == 0:
                residual = space_to_depth(residual, 2)
                residual = nn.Conv(self.filters, (1, 1), use_bias=False,
                                   dtype=self.dtype,
                                   kernel_init=nn.initializers.he_normal()
                                   )(residual)
            else:
                residual = nn.Conv(self.filters, (1, 1),
                                   strides=(self.strides, self.strides),
                                   use_bias=False, dtype=self.dtype,
                                   kernel_init=nn.initializers.he_normal()
                                   )(residual)
            residual = self.norm(use_running_average=not train,
                                 dtype=self.dtype)(residual)
        return nn.relu(y + residual)


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC space-to-depth: [B,H,W,C] -> [B,H/b,W/b,C*b*b].  A pure
    reshape/transpose — XLA lowers it to a layout change, no FLOPs."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h // block, w // block, c * block * block)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    stage_filters: Sequence[int]
    num_classes: int = 10
    stem_kernel: int = 3
    dtype: Any = jnp.float32
    # TPU stem experiment: fold a 2x2 space-to-depth into the stem so the
    # first conv sees 12 input channels at half resolution instead of 3 at
    # full — the standard MXU-friendliness trick for image stems.  On
    # CIFAR there is no stem downsampling to absorb the rearrangement, so
    # every stage runs at half resolution (a ~4x-fewer-FLOPs sibling of
    # ResNet-20, benchmarked as such), unlike ImageNet stems where the
    # trick is FLOP-neutral.  Flag-gated; default preserves the reference
    # architecture.
    stem_space_to_depth: bool = False
    # MXU-friendly transition shortcuts (see BasicBlock.mxu_shortcut)
    mxu_shortcuts: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = functools.partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5)
        x = x.astype(self.dtype)
        if self.stem_space_to_depth:
            x = space_to_depth(x, 2)
        k = self.stem_kernel
        x = nn.Conv(self.stage_filters[0], (k, k), padding="SAME",
                    use_bias=False, dtype=self.dtype,
                    kernel_init=nn.initializers.he_normal())(x)
        x = norm(use_running_average=not train, dtype=self.dtype)(x)
        x = nn.relu(x)
        for stage, (num_blocks, filters) in enumerate(
                zip(self.stage_sizes, self.stage_filters)):
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(filters, strides=strides, dtype=self.dtype,
                               norm=norm,
                               mxu_shortcut=self.mxu_shortcuts)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def ResNet20(num_classes: int = 10, dtype: Any = jnp.bfloat16,
             space_to_depth: bool = False,
             mxu_shortcuts: bool = False) -> ResNet:
    return ResNet(stage_sizes=(3, 3, 3), stage_filters=(16, 32, 64),
                  num_classes=num_classes, dtype=dtype,
                  stem_space_to_depth=space_to_depth,
                  mxu_shortcuts=mxu_shortcuts)


def ResNet32(num_classes: int = 10, dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(5, 5, 5), stage_filters=(16, 32, 64),
                  num_classes=num_classes, dtype=dtype)


def ResNet56(num_classes: int = 10, dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(9, 9, 9), stage_filters=(16, 32, 64),
                  num_classes=num_classes, dtype=dtype)


def ResNet18(num_classes: int = 10, dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), stage_filters=(64, 128, 256, 512),
                  num_classes=num_classes, dtype=dtype)
