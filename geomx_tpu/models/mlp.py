"""Small dense models for the MNIST-class workloads.

The reference's python frontend ships a gluon model zoo alongside the demo
CNN (reference: python/mxnet/gluon/model_zoo/vision/ — alexnet.py,
resnet.py, vgg.py, ...).  These are the dense members of ours: an MLP for
quick convergence tests and an AlexNet-style net sized for 32x32 inputs.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Dense net: flatten -> hidden relu layers -> logits."""

    num_classes: int = 10
    hidden: Sequence[int] = (256, 128)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        init = nn.initializers.xavier_uniform()
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h, kernel_init=init, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, kernel_init=init,
                        dtype=jnp.float32)(x)


class AlexNet(nn.Module):
    """AlexNet-style conv net adapted to 32x32 inputs (reference analogue:
    python/mxnet/gluon/model_zoo/vision/alexnet.py, with the stem scaled
    down so CIFAR-sized images survive the pooling pyramid)."""

    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        init = nn.initializers.xavier_uniform()
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(64, (3, 3), kernel_init=init, dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(192, (3, 3), kernel_init=init, dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(384, (3, 3), kernel_init=init, dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(256, (3, 3), kernel_init=init, dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(256, (3, 3), kernel_init=init, dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        x = nn.relu(nn.Dense(1024, kernel_init=init)(x))
        x = nn.relu(nn.Dense(512, kernel_init=init)(x))
        return nn.Dense(self.num_classes, kernel_init=init)(x)
