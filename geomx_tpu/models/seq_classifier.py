"""Long-context sequence classifier with sequence-parallel attention.

The drivable user of the sequence-parallel modules
(`parallel/ring_attention.py`, `parallel/ulysses.py`): a small
pre-LayerNorm transformer encoder whose attention runs, per the
``sp_mode`` flag,

- ``None``      — ordinary full attention (the un-meshed twin for
                  Trainer's init/eval paths, and the numerical baseline);
- ``"ring"``    — ring attention: K/V blocks rotate around the sp axis,
                  O(L/n) activations per device;
- ``"ulysses"`` — Ulysses: two all-to-alls re-shard sequence<->heads,
                  full-sequence streaming attention per head shard.

The SPMD contract with the train step (train/step.py): the step's
shard_map shards the token batch's SEQUENCE dim over the "sp" mesh axis
(``HiPSTopology(sp_degree=n)``), so this module receives its local
[B, L/n] chunk plus a matching chunk of GLOBAL positions; the mean-pool
is completed with a pmean over sp, making logits (and loss) identical on
every sp device; the step then psums grads over sp.  Both hierarchies
compose: dc/worker do HiPS data parallelism, sp does sequence
parallelism — the long-context capability beyond the reference's scope
(SURVEY.md §5 long-context; docs/long-context.md).

Input layout: int32 tokens of shape [B, L] — or [B, L, 2] where
``[..., 0]`` is the token id and ``[..., 1]`` its global position (what
the sp-sharded path uses, so position embeddings are correct without an
axis_index at init time).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from geomx_tpu.ops.flash_attention import fused_attention
from geomx_tpu.parallel.ring_attention import ring_attention
from geomx_tpu.parallel.ulysses import ulysses_attention
from geomx_tpu.topology import SP_AXIS


@jax.custom_vjp
def _scale_bwd(x, s):
    """Identity forward; backward multiplies the cotangent by ``s``.

    The gradient bookkeeping for mixing sequence-sharded and replicated
    regions in one shard_mapped step whose grads are psum'd over sp:
    params DOWNSTREAM of the pooling pmean see the full loss gradient on
    every sp device (psum would count them n times), while params
    UPSTREAM see only their shard's contribution (psum is exactly
    right — the pmean's transpose, a cotangent psum, already restores
    the full upstream gradient per shard).  Scaling the OUTPUT cotangent
    by 1/n fixes the replicated region without disturbing the sharded
    one, so one uniform psum reconstructs the true gradient for both."""
    return x


def _scale_bwd_fwd(x, s):
    return x, s


def _scale_bwd_bwd(s, g):
    return g * s, jnp.zeros_like(s)


_scale_bwd.defvjp(_scale_bwd_fwd, _scale_bwd_bwd)


class SPAttention(nn.Module):
    num_heads: int
    dim: int
    sp_mode: Optional[str] = None   # None | "ring" | "ulysses"
    causal: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h):
        B, L, D = h.shape
        hd = self.dim // self.num_heads
        qkv = nn.DenseGeneral((3, self.num_heads, hd), use_bias=False,
                              dtype=self.dtype, name="qkv")(h)
        q, k, v = (qkv[:, :, i] for i in range(3))  # [B, L, H, hd]
        if self.sp_mode == "ring":
            out = ring_attention(q, k, v, SP_AXIS, causal=self.causal)
        elif self.sp_mode == "ulysses":
            out = ulysses_attention(q, k, v, SP_AXIS, causal=self.causal)
        elif self.sp_mode is None:
            # un-meshed path: the fused Pallas kernel on TPU (no [L, L]
            # HBM materialization), the dense jnp reference elsewhere —
            # fused_attention dispatches; same math to f32 tolerance
            out = fused_attention(q, k, v, self.causal)
        else:
            raise ValueError(f"unknown sp_mode {self.sp_mode!r}")
        out = out.reshape(B, L, self.num_heads * hd)
        return nn.DenseGeneral(D, use_bias=False, dtype=self.dtype,
                               name="proj")(out)


class SeqClassifier(nn.Module):
    """Tiny encoder for sequence classification at long context."""

    vocab: int = 256
    max_len: int = 4096
    dim: int = 64
    num_heads: int = 4
    num_layers: int = 2
    num_classes: int = 10
    sp_mode: Optional[str] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:           # [B, L, 2]: (token, global position)
            tokens, pos = x[..., 0], x[..., 1]
        else:                     # [B, L]: positions are 0..L-1
            tokens = x
            pos = jnp.broadcast_to(jnp.arange(x.shape[1], dtype=jnp.int32),
                                   x.shape)
        h = nn.Embed(self.vocab, self.dim, dtype=self.dtype,
                     name="tok_embed")(tokens.astype(jnp.int32))
        h = h + nn.Embed(self.max_len, self.dim, dtype=self.dtype,
                         name="pos_embed")(pos.astype(jnp.int32))
        for i in range(self.num_layers):
            a = nn.LayerNorm(name=f"ln_a{i}")(h)
            h = h + SPAttention(self.num_heads, self.dim,
                                sp_mode=self.sp_mode, dtype=self.dtype,
                                name=f"attn{i}")(a)
            m = nn.LayerNorm(name=f"ln_m{i}")(h)
            m = nn.Dense(self.dim * 4, dtype=self.dtype,
                         name=f"mlp_in{i}")(m)
            h = h + nn.Dense(self.dim, dtype=self.dtype,
                             name=f"mlp_out{i}")(nn.gelu(m))
        pooled = jnp.mean(nn.LayerNorm(name="ln_f")(h), axis=1)
        if self.sp_mode is not None:
            # local means over equal-size chunks -> global mean; logits
            # (and the loss) become identical on every sp device.  The
            # pmean's transpose (a cotangent psum) already hands each
            # device the full upstream gradient for its shard path, so
            # the only correction needed is the 1/n on the output below.
            n = jnp.asarray(lax.psum(1, SP_AXIS), jnp.float32)
            pooled = lax.pmean(pooled, SP_AXIS)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32,
                          name="head")(pooled).astype(jnp.float32)
        if self.sp_mode is not None:
            # replicated-region params (the head) would otherwise get
            # their FULL gradient on every sp device and be over-counted
            # n-fold by the step's psum
            logits = _scale_bwd(logits, 1.0 / n)
        return logits
