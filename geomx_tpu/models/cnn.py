"""The reference demo CNN.

Architecture parity with examples/cnn.py:59-66: Conv(16, 5x5, relu) ->
MaxPool(2,2) -> Conv(32, 5x5, relu) -> MaxPool(2,2) -> Dense(256, relu) ->
Dense(128, relu) -> Dense(10), Xavier init.  Inputs are NHWC (TPU-native
layout; the reference uses NCHW because cuDNN prefers it — XLA on TPU
prefers channels-last).
"""

from __future__ import annotations

import flax.linen as nn


class GeoCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        init = nn.initializers.xavier_uniform()
        x = nn.Conv(16, (5, 5), kernel_init=init)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (5, 5), kernel_init=init)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256, kernel_init=init)(x))
        x = nn.relu(nn.Dense(128, kernel_init=init)(x))
        return nn.Dense(self.num_classes, kernel_init=init)(x)
