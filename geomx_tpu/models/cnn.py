"""The reference demo CNN.

Architecture parity with examples/cnn.py:59-66: Conv(16, 5x5, relu) ->
MaxPool(2,2) -> Conv(32, 5x5, relu) -> MaxPool(2,2) -> Dense(256, relu) ->
Dense(128, relu) -> Dense(10), Xavier init.  Inputs are NHWC (TPU-native
layout; the reference uses NCHW because cuDNN prefers it — XLA on TPU
prefers channels-last).

``dtype`` is the compute dtype (bf16 under ``GEOMX_PRECISION=bf16``);
params stay fp32 (flax casts per-op) and the classifier head computes
and returns fp32 like every model in the zoo.  The default ``None``
keeps flax's promotion rules — byte-identical to the historical trace.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class GeoCNN(nn.Module):
    num_classes: int = 10
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        init = nn.initializers.xavier_uniform()
        if self.dtype is not None:
            x = x.astype(self.dtype)
        x = nn.Conv(16, (5, 5), kernel_init=init, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (5, 5), kernel_init=init, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256, kernel_init=init, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(128, kernel_init=init, dtype=self.dtype)(x))
        head_dtype = None if self.dtype is None else jnp.float32
        x = nn.Dense(self.num_classes, kernel_init=init,
                     dtype=head_dtype)(x)
        return x if self.dtype is None else x.astype(jnp.float32)
