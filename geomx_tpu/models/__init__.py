"""Model zoo for the geo-distributed training workloads.

The reference's demo workloads are Gluon CNNs on MNIST/FashionMNIST/CIFAR10
(examples/cnn*.py); the flagship target is ResNet on CIFAR10 (BASELINE.md).
"""

import jax.numpy as jnp

from geomx_tpu.models.cnn import GeoCNN
from geomx_tpu.models.mlp import MLP, AlexNet
from geomx_tpu.models.resnet import (ResNet, ResNet18, ResNet20, ResNet32,
                                     ResNet56)
from geomx_tpu.models.seq_classifier import SeqClassifier

__all__ = ["GeoCNN", "MLP", "AlexNet",
           "ResNet", "ResNet20", "ResNet32", "ResNet56", "ResNet18",
           "SeqClassifier", "get_model"]

# GEOMX_PRECISION -> the models' compute dtype.  Params always stay
# fp32 (flax casts per-op from the fp32 masters); every model's
# classifier head computes and returns fp32 regardless (train/step.py).
_PRECISION_DTYPE = {"fp32": jnp.float32, "bf16": jnp.bfloat16}


def get_model(name: str, num_classes: int = 10, precision: str = None):
    """Build a zoo model.  ``precision`` (``"fp32"``/``"bf16"``, as
    resolved by ``train.step.resolve_precision``) pins the compute
    dtype explicitly; the default ``None`` keeps each model's
    historical default (byte-identical traces)."""
    name = name.lower()
    dt = {}
    if precision is not None:
        dt = {"dtype": _PRECISION_DTYPE[precision]}
    if name in ("cnn", "geocnn", "lenet"):
        return GeoCNN(num_classes=num_classes, **dt)
    if name == "mlp":
        return MLP(num_classes=num_classes, **dt)
    if name == "alexnet":
        return AlexNet(num_classes=num_classes, **dt)
    if name == "resnet20":
        return ResNet20(num_classes=num_classes, **dt)
    if name in ("resnet20_s2d", "resnet20-s2d"):
        # TPU-optimized variant: 2x2 space-to-depth stem + MXU-friendly
        # transition shortcuts (see models/resnet.py)
        return ResNet20(num_classes=num_classes, space_to_depth=True,
                        mxu_shortcuts=True, **dt)
    if name == "resnet32":
        return ResNet32(num_classes=num_classes, **dt)
    if name == "resnet56":
        return ResNet56(num_classes=num_classes, **dt)
    if name == "resnet18":
        return ResNet18(num_classes=num_classes, **dt)
    if name in ("seq", "seq_classifier", "transformer"):
        return SeqClassifier(num_classes=num_classes, **dt)
    raise ValueError(f"Unknown model: {name!r}")
