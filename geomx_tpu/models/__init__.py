"""Model zoo for the geo-distributed training workloads.

The reference's demo workloads are Gluon CNNs on MNIST/FashionMNIST/CIFAR10
(examples/cnn*.py); the flagship target is ResNet on CIFAR10 (BASELINE.md).
"""

from geomx_tpu.models.cnn import GeoCNN
from geomx_tpu.models.mlp import MLP, AlexNet
from geomx_tpu.models.resnet import (ResNet, ResNet18, ResNet20, ResNet32,
                                     ResNet56)
from geomx_tpu.models.seq_classifier import SeqClassifier

__all__ = ["GeoCNN", "MLP", "AlexNet",
           "ResNet", "ResNet20", "ResNet32", "ResNet56", "ResNet18",
           "SeqClassifier", "get_model"]


def get_model(name: str, num_classes: int = 10):
    name = name.lower()
    if name in ("cnn", "geocnn", "lenet"):
        return GeoCNN(num_classes=num_classes)
    if name == "mlp":
        return MLP(num_classes=num_classes)
    if name == "alexnet":
        return AlexNet(num_classes=num_classes)
    if name == "resnet20":
        return ResNet20(num_classes=num_classes)
    if name in ("resnet20_s2d", "resnet20-s2d"):
        # TPU-optimized variant: 2x2 space-to-depth stem + MXU-friendly
        # transition shortcuts (see models/resnet.py)
        return ResNet20(num_classes=num_classes, space_to_depth=True,
                        mxu_shortcuts=True)
    if name == "resnet32":
        return ResNet32(num_classes=num_classes)
    if name == "resnet56":
        return ResNet56(num_classes=num_classes)
    if name == "resnet18":
        return ResNet18(num_classes=num_classes)
    if name in ("seq", "seq_classifier", "transformer"):
        return SeqClassifier(num_classes=num_classes)
    raise ValueError(f"Unknown model: {name!r}")
