"""Run capsules: whole-run telemetry capture + bit-exact offline replay.

The observability planes built across PRs 5-13 each dump their own
artifact — registry values are point-in-time, Chrome traces, the event
log, the fleet round ledger and the Pilot's decision log land in
disjoint files with no shared manifest — so nothing reconstructs *a
run* offline.  :class:`RunCapsule` fixes that: one recorder snapshots
the full observability state of a training run into ONE versioned,
atomically-written archive, and :class:`Capsule` reconstructs the
run's sensor surfaces offline, **bit-identically**:

- a **manifest**: the resolved :class:`~geomx_tpu.config.GeoConfig`,
  every ``GEOMX_*``/reference-alias env knob, the chaos schedule,
  build identity and a wall-clock anchor;
- a **registry time series**: periodic full samples of every Counter /
  Gauge / Histogram (:class:`RegistrySampler` — the sampling loop the
  registry itself never had), plus per-step records of the
  ``geomx_step_probe`` / ``geomx_phase_fraction`` gauge families at
  each publish boundary (what :class:`~geomx_tpu.control.sensors.
  ControlSensors` actually reads);
- a **link journal**: every :meth:`LinkObservatory.observe` call with
  its RESOLVED timestamp (the :meth:`~geomx_tpu.telemetry.links.
  LinkObservatory.set_tap` hook) — replaying the journal through a
  fresh observatory in order reproduces the EWMA state, and therefore
  every ``snapshot(now=...)``, bit-identically;
- the Chrome trace(s), the bounded event log, the fleet round ledger
  and the Pilot decision log, all in one archive.

Offline, :meth:`Capsule.sensors` rebuilds the
:class:`~geomx_tpu.control.sensors.ControlSensors` observation stream
(per-step registry views + a journal-fed replay observatory), so a
:class:`~geomx_tpu.control.policy.GraftPilot` re-ticked over the
capsule reproduces the live decision sequence exactly — the
deterministic-replay substrate the Pilot-v2 offline planner search
(ROADMAP item 5) and the fitted step-time cost model
(:mod:`geomx_tpu.telemetry.costmodel`) build on.

Gated by ``GEOMX_CAPSULE`` / ``GeoConfig(capsule=True)``; archive
location ``GEOMX_CAPSULE_DIR``, sampler cadence
``GEOMX_CAPSULE_SAMPLE_S`` (docs/telemetry.md "Run capsules").
Everything here is host-plane Python — no jax import.
"""

from __future__ import annotations

import collections
import os
import platform
import sys
import threading
import time
from typing import Any, Dict, List, Optional

CAPSULE_KIND = "geomx_run_capsule"
CAPSULE_VERSION = 1

DEFAULT_SAMPLE_S = 10.0
DEFAULT_MAX_SAMPLES = 512
DEFAULT_MAX_STEPS = 4096
DEFAULT_MAX_JOURNAL = 262_144
DEFAULT_MAX_TRACES = 8

# env prefixes the manifest resolves (the GEOMX_* surface plus the
# reference aliases config.py honors and the backend-shaping vars)
_ENV_PREFIXES = ("GEOMX_", "DMLC_", "MXNET_", "JAX_", "XLA_")


def _geomx_version() -> str:
    try:
        from importlib.metadata import version
        return version("geomx-tpu")
    except Exception:
        return "unknown"


# ---------------------------------------------------------------------------
# registry sampling (the time-series loop the registry never had)
# ---------------------------------------------------------------------------

def sample_registry(registry=None,
                    max_children_per_family: int = 0) -> Dict[str, dict]:
    """One full, JSON-able snapshot of every registry family: counters
    and gauges as values, histograms as (bounds, bucket counts, sum,
    count).  ``max_children_per_family`` bounds high-cardinality
    families (dropped children are counted, never silently lost) —
    the flight recorder's bundle section uses it to keep the same size
    discipline as its ring."""
    from geomx_tpu.telemetry.registry import HistogramChild, get_registry
    reg = registry if registry is not None else get_registry()
    out: Dict[str, dict] = {}
    for fam in reg.collect():
        children = fam.children()
        dropped = 0
        if max_children_per_family and \
                len(children) > max_children_per_family:
            dropped = len(children) - max_children_per_family
            children = children[:max_children_per_family]
        rows: List[dict] = []
        for values, child in children:
            row: Dict[str, Any] = {"labels": list(values)}
            if isinstance(child, HistogramChild):
                cum, total, count = child.snapshot()
                row.update(buckets=list(child.upper_bounds),
                           counts=cum, sum=total, count=count)
            else:
                row["value"] = child.value
            rows.append(row)
        entry: Dict[str, Any] = {"type": fam.type,
                                 "label_names": list(fam.label_names),
                                 "children": rows}
        if dropped:
            entry["dropped_children"] = dropped
        out[fam.name] = entry
    return out


class RegistrySampler:
    """Periodic whole-registry sampler: a bounded time series of
    :func:`sample_registry` snapshots.  :meth:`sample` takes one sample
    at an explicit ``now`` (the bench's virtual clock); :meth:`start`
    runs a wall-clock daemon loop at ``interval_s`` for live runs."""

    def __init__(self, registry=None, interval_s: float = DEFAULT_SAMPLE_S,
                 max_samples: int = DEFAULT_MAX_SAMPLES):
        self.registry = registry
        # a non-positive cadence would make the daemon loop's
        # stop.wait(0) a busy spin walking the whole registry — clamp
        # to the documented default ("0 = 10 s", config.py)
        self.interval_s = float(interval_s) if interval_s \
            and float(interval_s) > 0 else DEFAULT_SAMPLE_S
        self.samples: "collections.deque[dict]" = collections.deque(
            maxlen=max(1, int(max_samples)))
        self.dropped = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def sample(self, now: Optional[float] = None) -> dict:
        entry = {"t": time.time() if now is None else float(now),
                 "families": sample_registry(self.registry)}
        with self._lock:
            if len(self.samples) == self.samples.maxlen:
                self.dropped += 1
            self.samples.append(entry)
        return entry

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self.samples)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample()
                except Exception:
                    pass  # sampling must never take down the run

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="capsule-sampler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------

def _gauge_map(registry, family: str) -> Dict[str, float]:
    """{first-label-value: value} over one gauge family — the exact
    read :class:`ControlSensors` performs, duplicated here so telemetry
    never imports control (control imports telemetry)."""
    fam = registry.get(family)
    if fam is None:
        return {}
    out: Dict[str, float] = {}
    for label_values, child in fam.children():
        out[label_values[0] if label_values else ""] = float(child.value)
    return out


class RunCapsule:
    """Record one training run's whole observability state into a
    single versioned archive at ``path`` (atomic on every
    :meth:`write`, via :mod:`geomx_tpu.utils.atomicio`).

    The recorder is fed from four directions: per-step records at the
    trainer's publish boundary (:meth:`record_step`), the link journal
    via :meth:`attach_observatory`, periodic registry samples
    (:attr:`sampler`), and run-scoped artifacts collected at
    :meth:`write` time (traces, event log, round ledger, decision
    log).  Every buffer is bounded with a dropped counter — a capsule
    whose journal overflowed says so instead of replaying wrong.
    """

    def __init__(self, path: str, *, config=None,
                 sample_s: float = DEFAULT_SAMPLE_S,
                 registry=None,
                 max_steps: int = DEFAULT_MAX_STEPS,
                 max_journal: int = DEFAULT_MAX_JOURNAL,
                 extra_manifest: Optional[dict] = None):
        self.path = str(path)
        # reclaim orphans a hard kill mid-write left behind (the
        # archive rewrites at every fit end; see atomicio)
        from geomx_tpu.utils.atomicio import sweep_stale_tmp
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        sweep_stale_tmp(d)
        self.registry = registry
        self._lock = threading.Lock()
        self._steps: "collections.deque[dict]" = collections.deque(
            maxlen=max(1, int(max_steps)))
        self.steps_dropped = 0
        self._journal: "collections.deque[dict]" = collections.deque(
            maxlen=max(1, int(max_journal)))
        self.journal_dropped = 0
        self._traces: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._observatory = None
        self.sampler = RegistrySampler(registry=registry,
                                       interval_s=sample_s)
        self.writes = 0
        cfg_dict = None
        if config is not None:
            import dataclasses
            cfg_dict = dataclasses.asdict(config) \
                if dataclasses.is_dataclass(config) else dict(config)
        # graftlint: disable=GXL006 — the manifest's whole job is
        # recording the resolved env surface at run start
        env = {k: v for k, v in sorted(os.environ.items())
               if k.startswith(_ENV_PREFIXES)}
        self.manifest: Dict[str, Any] = {
            "kind": CAPSULE_KIND,
            "version": CAPSULE_VERSION,
            "created_unix": round(time.time(), 6),
            "anchor_unix": round(time.time(), 6),
            "config": cfg_dict,
            "env": env,
            "chaos_schedule": (cfg_dict or {}).get("chaos_schedule", "")
            or env.get("GEOMX_CHAOS_SCHEDULE", ""),
            "sample_s": float(sample_s),
            "build": {
                "geomx_version": _geomx_version(),
                "python": sys.version.split()[0],
                "platform": platform.platform(),
            },
        }
        if extra_manifest:
            self.manifest["extra"] = dict(extra_manifest)

    # ---- feeds -------------------------------------------------------------

    def attach_observatory(self, observatory) -> None:
        """Install the link-journal tap on ``observatory`` and record
        its fold parameters in the manifest (the replay observatory is
        reconstructed with the same alpha / staleness half-life)."""
        self._observatory = observatory
        self.manifest["observatory"] = {
            "alpha": observatory.alpha,
            "stale_after_s": observatory.stale_after_s,
        }
        observatory.set_tap(self._link_tap)

    def detach_observatory(self) -> None:
        if self._observatory is not None:
            self._observatory.set_tap(None)
            self._observatory = None

    def _link_tap(self, entry: dict) -> None:
        # called under the observatory lock (journal order == fold
        # order); the capsule lock nests inside it so write() can
        # snapshot the journal from another thread — never take the
        # observatory lock while holding the capsule lock
        with self._lock:
            if len(self._journal) == self._journal.maxlen:
                self.journal_dropped += 1
            self._journal.append(entry)

    def record_step(self, step: int, t: Optional[float] = None,
                    probes: Optional[Dict[str, Any]] = None,
                    phases: Optional[Dict[str, float]] = None,
                    timing: Optional[Dict[str, float]] = None,
                    extra: Optional[Dict[str, Any]] = None) -> dict:
        """Record one step's sensor surface.  ``probes``/``phases``
        default to the live ``geomx_step_probe`` /
        ``geomx_phase_fraction`` gauge families — exactly what a
        control tick at this moment would read, which is what makes
        the replayed observation stream bit-identical.  ``t`` is the
        run clock at the record (virtual in seeded replays; wall clock
        in live runs); ``timing`` carries measured per-step seconds
        (``total_s`` / ``compute_s`` / ``wan_s`` / ``exposed_s``) the
        cost model fits on."""
        if probes is None or phases is None:
            from geomx_tpu.telemetry.registry import get_registry
            reg = self.registry if self.registry is not None \
                else get_registry()
            if probes is None:
                probes = _gauge_map(reg, "geomx_step_probe")
            if phases is None:
                phases = _gauge_map(reg, "geomx_phase_fraction")
        rec: Dict[str, Any] = {
            "step": int(step),
            "t": time.time() if t is None else float(t),
            "probes": dict(probes),
            "phases": dict(phases),
        }
        if timing:
            rec["timing"] = {k: float(v) for k, v in timing.items()}
        if extra:
            rec["extra"] = dict(extra)
        with self._lock:
            if len(self._steps) == self._steps.maxlen:
                self.steps_dropped += 1
            self._steps.append(rec)
        return rec

    def set_param_shapes(self, shapes: Dict[str, dict]) -> None:
        """Record the model's flat parameter layout
        (``{path: {"shape": [...], "dtype": "float32"}}``) — the cost
        model's input for candidate wire-byte accounting."""
        self.manifest["param_shapes"] = {
            str(k): {"shape": [int(d) for d in v["shape"]],
                     "dtype": str(v["dtype"])}
            for k, v in shapes.items()}

    def add_trace(self, doc: dict, label: str = "rank0") -> None:
        """Attach one Chrome trace document (``Profiler.to_doc()`` /
        ``merge_traces`` output).  Re-adding a label replaces it, so a
        trainer can refresh its trace at every write; the trace count
        is bounded at the oldest-label eviction."""
        with self._lock:
            self._traces[str(label)] = doc
            self._traces.move_to_end(str(label))
            while len(self._traces) > DEFAULT_MAX_TRACES:
                self._traces.popitem(last=False)

    # ---- archive -----------------------------------------------------------

    def _summary(self, steps: List[dict], journal: List[dict],
                 now: Optional[float] = None) -> dict:
        """Pre-computed cross-section summary stored IN the archive so
        ``tools/runcap.py diff``/``explain`` (and benchtrend's
        regression explainer) stay stdlib-only readers."""
        out: Dict[str, Any] = {"num_steps": len(steps)}
        if steps:
            out["first_t"] = steps[0]["t"]
            out["last_t"] = steps[-1]["t"]
            phase_acc: Dict[str, List[float]] = {}
            probe_acc: Dict[str, List[float]] = {}
            for rec in steps:
                for k, v in rec.get("phases", {}).items():
                    phase_acc.setdefault(k, []).append(float(v))
                for k, v in rec.get("probes", {}).items():
                    if isinstance(v, (int, float)):
                        probe_acc.setdefault(k, []).append(float(v))
            out["phase_means"] = {
                k: sum(v) / len(v) for k, v in sorted(phase_acc.items())}
            out["probe_medians"] = {
                k: sorted(v)[len(v) // 2]
                for k, v in sorted(probe_acc.items())}
        # whole-run per-link aggregates from the journal: a diff between
        # two RUNS must see a mid-run degradation even when the final
        # EWMA state has recovered by run end
        agg: Dict[str, dict] = {}
        for e in journal:
            a = agg.setdefault(f"{e['party']}->{e['peer']}", {
                "samples": 0, "failures": 0, "ok_timed": 0,
                "bytes": 0.0, "seconds": 0.0, "min_bps": None})
            a["samples"] += 1
            if not e.get("ok", True):
                a["failures"] += 1
                continue
            sec = e.get("seconds")
            if not sec:
                continue
            nb = float(e.get("nbytes") or 0.0)
            a["ok_timed"] += 1
            a["seconds"] += float(sec)
            a["bytes"] += nb
            if nb > 0:
                bps = nb / float(sec)
                if a["min_bps"] is None or bps < a["min_bps"]:
                    a["min_bps"] = bps
        out["links"] = {
            k: {
                "throughput_bps": (a["bytes"] / a["seconds"])
                if a["seconds"] and a["bytes"] else None,
                "rtt_s": (a["seconds"] / a["ok_timed"])
                if a["ok_timed"] else None,
                "loss_rate": a["failures"] / a["samples"],
                "min_throughput_bps": a["min_bps"],
                "samples": a["samples"],
            } for k, a in sorted(agg.items())}
        if self._observatory is not None:
            snap_now = now
            if snap_now is None and journal:
                snap_now = journal[-1]["t"]
            out["links_final"] = self._observatory.snapshot(now=snap_now)
        try:
            from geomx_tpu.telemetry.ledger import get_round_ledger
            led_summary = get_round_ledger().summary(now=now)
            if "wire_honesty_ratio_mean" in led_summary:
                out["wire_honesty_ratio"] = \
                    led_summary["wire_honesty_ratio_mean"]
        except Exception:
            pass
        return out

    def write(self, now: Optional[float] = None,
              include_ledger: bool = True,
              include_events: bool = True,
              include_decisions: bool = True) -> str:
        """Write the whole archive atomically (safe to call repeatedly
        — a crash between writes leaves the previous complete capsule).
        ``now`` pins the clock-dependent summary fields in seeded
        replays."""
        with self._lock:
            steps = list(self._steps)
            journal = list(self._journal)
            traces = [{"label": label, "doc": doc}
                      for label, doc in self._traces.items()]
        doc: Dict[str, Any] = {
            "manifest": dict(self.manifest,
                             written_unix=round(time.time(), 6),
                             steps_dropped=self.steps_dropped,
                             journal_dropped=self.journal_dropped,
                             samples_dropped=self.sampler.dropped),
            "registry_samples": self.sampler.snapshot(),
            "steps": steps,
            "link_journal": journal,
            "traces": traces,
        }
        if include_ledger:
            try:
                from geomx_tpu.telemetry.ledger import get_round_ledger
                led = get_round_ledger()
                doc["ledger"] = {"records": led.records(),
                                 "summary": led.summary(now=now)}
            except Exception:
                doc["ledger"] = {"records": [], "summary": {}}
        if include_events:
            try:
                from geomx_tpu.telemetry.export import get_event_log
                log = get_event_log()
                doc["events"] = log.read() if log is not None else []
            except Exception:
                doc["events"] = []
        if include_decisions:
            try:
                from geomx_tpu.control.actuators import get_decision_log
                doc["decisions"] = get_decision_log().snapshot()
            except Exception:
                doc["decisions"] = []
        doc["summary"] = self._summary(steps, journal, now=now)
        from geomx_tpu.utils.atomicio import atomic_json_dump
        path = atomic_json_dump(self.path, doc,
                                default=_capsule_json_default)
        self.writes += 1
        return path

    def close(self, now: Optional[float] = None) -> str:
        """Stop the sampler, detach the tap and write the final
        archive."""
        self.sampler.stop()
        path = self.write(now=now)
        self.detach_observatory()
        return path


def _capsule_json_default(o):
    item = getattr(o, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return repr(o)


# ---------------------------------------------------------------------------
# loader / replay
# ---------------------------------------------------------------------------

class _GaugeView:
    __slots__ = ("value",)

    def __init__(self, value: float):
        self.value = float(value)


class _FamilyView:
    """Registry-family stand-in over one recorded mapping
    ``{label_value: float}`` — implements exactly the surface
    ``ControlSensors`` reads (``children()``)."""

    def __init__(self, mapping: Dict[str, float]):
        self._mapping = mapping

    def children(self):
        return sorted(((str(k),), _GaugeView(v))
                      for k, v in self._mapping.items())


class _StepRegistryView:
    """The registry as one recorded step saw it: the two gauge
    families the control sensors read, served from the step record."""

    def __init__(self, rec: dict):
        self._fams = {
            "geomx_step_probe": _FamilyView(
                {k: v for k, v in rec.get("probes", {}).items()
                 if isinstance(v, (int, float))}),
            "geomx_phase_fraction": _FamilyView(
                {k: float(v) for k, v in rec.get("phases", {}).items()}),
        }

    def get(self, name: str):
        return self._fams.get(name)


class _ReplayObservatory:
    """A :class:`LinkObservatory` fed lazily from the capsule's link
    journal: before every snapshot at ``now``, all journal entries
    with ``t <= now`` (in append order — which recorded fold order)
    are folded in, so the EWMA state at any replay instant is
    bit-identical to the live state at that instant.  Entries later
    than ``now`` stay pending — a replayed controller never sees the
    future."""

    def __init__(self, journal: List[dict], alpha: float,
                 stale_after_s: float):
        from geomx_tpu.telemetry.links import LinkObservatory
        self._obs = LinkObservatory(alpha=alpha,
                                    stale_after_s=stale_after_s)
        self._journal = journal
        self._idx = 0

    def _feed_upto(self, now: Optional[float]) -> None:
        while self._idx < len(self._journal):
            e = self._journal[self._idx]
            if now is not None and e["t"] > now:
                return
            self._obs.observe(e["party"], e["peer"],
                              nbytes=e.get("nbytes", 0.0),
                              seconds=e.get("seconds"),
                              ok=e.get("ok", True), t=e["t"])
            self._idx += 1

    def snapshot(self, now: Optional[float] = None,
                 min_confidence: Optional[float] = None):
        self._feed_upto(now)
        return self._obs.snapshot(now=now, min_confidence=min_confidence)

    def best_relay_order(self, peer: str = "global",
                         now: Optional[float] = None,
                         min_confidence: float = 0.0):
        self._feed_upto(now)
        return self._obs.best_relay_order(peer=peer, now=now,
                                          min_confidence=min_confidence)


class Capsule:
    """A loaded run capsule: the archive's sections plus the offline
    reconstruction surfaces (replay observatory, per-step registry
    views, sensor stream, decision replay)."""

    def __init__(self, doc: dict, path: Optional[str] = None):
        manifest = doc.get("manifest") or {}
        if manifest.get("kind") != CAPSULE_KIND:
            raise ValueError(
                f"not a run capsule (kind={manifest.get('kind')!r})")
        if manifest.get("version") != CAPSULE_VERSION:
            raise ValueError(
                f"unsupported capsule version {manifest.get('version')!r}"
                f" (this build reads version {CAPSULE_VERSION})")
        self.doc = doc
        self.path = path
        self.manifest = manifest
        self.steps: List[dict] = doc.get("steps") or []
        self.link_journal: List[dict] = doc.get("link_journal") or []
        self.registry_samples: List[dict] = \
            doc.get("registry_samples") or []
        self.traces: List[dict] = doc.get("traces") or []
        self.ledger: dict = doc.get("ledger") or {}
        self.events: List[dict] = doc.get("events") or []
        self.decisions: List[dict] = doc.get("decisions") or []
        self.summary: dict = doc.get("summary") or {}

    @classmethod
    def load(cls, path: str) -> "Capsule":
        import json
        with open(path) as f:
            return cls(json.load(f), path=path)

    # ---- replay surfaces ---------------------------------------------------

    def _obs_params(self):
        p = self.manifest.get("observatory") or {}
        return float(p.get("alpha", 0.3)), \
            float(p.get("stale_after_s", 30.0))

    def observatory(self) -> _ReplayObservatory:
        """A fresh replay observatory over the link journal (nothing
        folded yet — feeds advance with each ``snapshot(now=...)``)."""
        alpha, stale = self._obs_params()
        return _ReplayObservatory(self.link_journal, alpha, stale)

    def link_snapshot(self, now: Optional[float] = None,
                      min_confidence: Optional[float] = None) -> dict:
        """The per-link snapshot at ``now`` (default: after the whole
        journal) — bit-identical to what the live observatory reported
        at that instant."""
        obs = self.observatory()
        if now is None and self.link_journal:
            now = self.link_journal[-1]["t"]
        return obs.snapshot(now=now, min_confidence=min_confidence)

    def registry_at(self, step: int):
        """The control-sensor registry view recorded at ``step`` (the
        latest record at or before it)."""
        best = None
        for rec in self.steps:
            if rec["step"] <= int(step):
                best = rec
            else:
                break
        if best is None:
            return _StepRegistryView({})
        return _StepRegistryView(best)

    def sensors(self, min_confidence: float = 0.5, compute_s_fn=None):
        """A :class:`~geomx_tpu.control.sensors.ControlSensors` whose
        ``observe(step, now)`` reads the capsule instead of the live
        planes — the offline observation stream."""
        from geomx_tpu.control.sensors import ControlSensors
        return ControlSensors(observatory=self.observatory(),
                              min_confidence=min_confidence,
                              compute_s_fn=compute_s_fn,
                              registry_fn=self.registry_at)

    def replay_decisions(self, pilot_factory,
                         min_confidence: float = 0.5,
                         compute_s_fn=None) -> List[dict]:
        """Re-tick a Pilot over the capsule: ``pilot_factory(sensors)``
        must build the same policy stack the live run used (policies
        are pure functions of their constructor args + observations,
        so identical observations reproduce the live decision sequence
        exactly).  Returns the decisions' JSON forms, comparable
        against the live ``DecisionLog.snapshot()``."""
        sensors = self.sensors(min_confidence=min_confidence,
                               compute_s_fn=compute_s_fn)
        pilot = pilot_factory(sensors)
        out: List[dict] = []
        for rec in self.steps:
            for dec in pilot.tick(rec["step"], now=rec.get("t")):
                out.append(dec.to_json())
        return out


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

def capsule_enabled(config: Optional[Any] = None) -> bool:
    """``GeoConfig(capsule=True)`` or ``GEOMX_CAPSULE`` (same
    numeric-boolean parse as every GEOMX_* knob)."""
    if config is not None and getattr(config, "capsule", False):
        return True
    from geomx_tpu.config import _env_bool
    return _env_bool(["GEOMX_CAPSULE"], False)


def capsule_from_config(config: Optional[Any] = None
                        ) -> Optional[RunCapsule]:
    """The trainer's constructor path: None when recording is off;
    otherwise a recorder at ``<GEOMX_CAPSULE_DIR>/run_capsule.json``
    sampling every ``GEOMX_CAPSULE_SAMPLE_S`` seconds."""
    if not capsule_enabled(config):
        return None
    from geomx_tpu.config import _env
    cap_dir = getattr(config, "capsule_dir", "") or \
        _env(["GEOMX_CAPSULE_DIR"], "geomx_capsule", str)
    sample_s = getattr(config, "capsule_sample_s", 0.0) or \
        _env(["GEOMX_CAPSULE_SAMPLE_S"], DEFAULT_SAMPLE_S, float)
    return RunCapsule(os.path.join(cap_dir, "run_capsule.json"),
                      config=config, sample_s=sample_s)
