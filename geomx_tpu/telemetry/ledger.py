"""Fleet round ledger: causal per-round tracing + byte-true wire accounting.

The host plane grew to a durable, key-range-sharded fleet (PRs 10-12)
but its observability stayed per-process: counters count, spans span,
and nothing reconstructs what actually happened to gradient round 7 of
``conv1.weight`` — which parties pushed it (and in how many P3
chunks), which shard merged it, whether a redirect or a corrupted
frame or a session-resume replay touched it on the way, and how many
bytes it REALLY cost on the socket versus what the compressor claimed.

:class:`RoundLedger` is that reconstruction, one record per
``(key, round)``:

- a **hop chain**: every causally-ordered event of the round — client
  push (one hop per frame, so each P3 chunk and each reconnect replay
  is visible), ``wrong_shard`` redirects, session-resume /failover
  replays, chaos-injected corruption, the merge-gate close, the
  durable journal write, the WAN relay, and the pull replies — each
  hop carrying party, shard, wall-clock timestamp, duration and bytes;
- **byte-true wire accounting**: frame bytes are counted at the one
  ``Msg.encode``/``Msg.decode`` choke point every producer and
  consumer shares (``service/protocol.py``), attributed per round and
  direction, and reconciled against the sender-declared payload bytes
  (``meta["wire_declared"]``) into a per-round **honesty ratio** —
  GX-DTYPE-002's wire-honesty guarantee extended from the traced jaxpr
  to the physical wire, now covering P3 framing, the pair codec, the
  CRC prelude and pickled headers that no in-graph audit can see;
- **phase breakdown**: queue / gate-wait / merge / journal / reply
  seconds per round, also observed into the per-shard
  ``geomx_round_phase_seconds{shard,phase}`` histogram;
- bounded memory like every other ring: completed records evict FIFO
  past ``GEOMX_LEDGER_ROUNDS`` (default 256, counted in
  ``geomx_ledger_evictions_total``), and an abandoned open round (a
  failed shard, an evicted sender, a round id that never completed)
  closes as ``status="orphaned"`` instead of leaking.

Read surfaces: :meth:`RoundLedger.records` (dict snapshots — served as
``GET /ledger`` by the scheduler's and GeoPSServer's HTTP exporters),
:meth:`RoundLedger.to_doc` (a ``merge_traces``-compatible Chrome trace
document, so the merged timeline shows the full fleet round),
:meth:`RoundLedger.summary` (the scalars the FlightRecorder's
``stuck_round`` / ``honesty_ratio_drift`` rules and the Pilot's
sensors consume), and the bounded event log (one ``round_ledger``
event per completed/orphaned round).

Everything here is host-plane Python — no jax import, safe in the
jax-free scheduler process.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_ROUNDS = 256

# ---- hop catalog (docs/telemetry.md "Round ledger") ----------------------
PUSH = "push"                 # client: one PUSH frame submitted (P3 chunk
#                               detail in ``detail["chunk"]``)
REDIRECT = "redirect"         # client: a wrong_shard redirect absorbed
REPLAY = "replay"             # client: session-resume re-push after a
#                               server restart (generation changed)
FAILOVER_REPLAY = "failover_replay"   # sharded wrapper: re-push after a
#                               failover re-join (map re-point)
CORRUPT = "corrupt"           # chaos: a bit flip injected into this
#                               round's frame at the sender
MERGE = "merge"               # server: the sync gate closed and the
#                               round's contributions merged
JOURNAL = "journal"           # server: the round's durable journal write
RELAY = "relay"               # server: the WAN relay hop (local->global)
REPLY = "reply"               # server: pull replies for the round

FAULT_HOPS = (REDIRECT, REPLAY, FAILOVER_REPLAY, CORRUPT)

PHASES = ("queue", "gate_wait", "merge", "journal", "reply")

# wire-accounting kinds, from the frame's MsgType at the encode/decode
# choke point
_WIRE_KINDS = {"PUSH": "push", "PULL_REPLY": "reply", "RELAY": "relay"}

# documented clean-link framing bounds: one frame's overhead over its
# declared payload never exceeds these — the reconciliation gate's
# per-frame allowance.  512 B is the LEGACY pickled codec's bound
# (version+CRC prelude, length words, pickled header); the binary v0x02
# codec's exact header-size bound is much tighter (192 B, derived
# field-by-field in service/protocol.py as BIN_FRAME_OVERHEAD_BOUND)
# and :func:`active_frame_overhead_bound` resolves whichever codec is
# encoding.
FRAME_OVERHEAD_BOUND = 512

# clean-round honesty assertion under the binary codec: measured push
# bytes over declared payload bytes must stay within 2% — only asserted
# when the average frame payload clears the floor below (tiny control
# payloads are legitimately header-dominated and say nothing about wire
# honesty)
HONESTY_BOUND = 1.02
HONESTY_MIN_FRAME_PAYLOAD = 4096


def active_frame_overhead_bound() -> int:
    """The per-frame framing allowance for whichever codec
    ``Msg.encode`` is currently producing: the exact binary-frame
    header bound under the default v0x02 codec, the legacy 512 B
    pickled-header allowance under ``GEOMX_NATIVE_WIRE=0``."""
    from geomx_tpu.service.protocol import (BIN_FRAME_OVERHEAD_BOUND,
                                            binary_wire_enabled)
    return BIN_FRAME_OVERHEAD_BOUND if binary_wire_enabled() \
        else FRAME_OVERHEAD_BOUND


def _ledger_capacity() -> int:
    from geomx_tpu.config import _env
    return max(1, _env(("GEOMX_LEDGER_ROUNDS",), DEFAULT_ROUNDS, int))


class RoundRecord:
    """One (key, round)'s accumulating state.  Mutated only under the
    owning ledger's lock; :meth:`snapshot` returns the plain-dict view
    every read surface serves."""

    __slots__ = ("key", "round", "origin_party", "status", "opened_unix",
                 "closed_unix", "hops", "wire", "declared_tx",
                 "declared_rx", "phases", "detail")

    def __init__(self, key: str, round_id: int):
        self.key = key
        self.round = int(round_id)
        self.origin_party: Optional[int] = None
        self.status = "open"
        self.opened_unix = time.time()
        self.closed_unix: Optional[float] = None
        self.hops: List[dict] = []
        self.wire: "collections.Counter" = collections.Counter()
        self.declared_tx = 0
        self.declared_rx = 0
        self.phases: Dict[str, float] = {}
        self.detail: Dict[str, Any] = {}

    # -- derived -----------------------------------------------------------

    def hop_kinds(self) -> List[str]:
        return [h["hop"] for h in self.hops]

    def fault_hops(self) -> List[dict]:
        return [h for h in self.hops if h["hop"] in FAULT_HOPS]

    def honesty_ratio(self) -> Optional[float]:
        """Measured push-frame bytes over sender-declared payload bytes.
        Prefers the receive side (it sees retransmitted frames the
        encode side only encoded once); falls back to the send side in
        a pure-sender process.  None before any declared push bytes."""
        if self.declared_rx > 0:
            return self.wire.get("push_rx_bytes", 0) / self.declared_rx
        if self.declared_tx > 0:
            return self.wire.get("push_tx_bytes", 0) / self.declared_tx
        return None

    def reconciles(self, per_frame_bound: Optional[int] = None,
                   honesty_bound: Optional[float] = None) -> bool:
        """The byte-true reconciliation gate for a CLEAN round (callers
        filter on :meth:`fault_hops`): measured push bytes cover the
        declared payload exactly once plus at most ``per_frame_bound``
        framing overhead per frame (docs/telemetry.md states the
        bounds; ``None`` resolves the active codec's bound via
        :func:`active_frame_overhead_bound`).  Under the binary codec
        the gate additionally ASSERTS declared ≈ measured — honesty
        ratio ≤ ``honesty_bound`` (default :data:`HONESTY_BOUND`) —
        whenever the average frame payload clears
        :data:`HONESTY_MIN_FRAME_PAYLOAD`; pass an explicit
        ``honesty_bound`` to force or loosen that check."""
        if per_frame_bound is None:
            per_frame_bound = active_frame_overhead_bound()
        if self.declared_rx > 0:
            measured = self.wire.get("push_rx_bytes", 0)
            frames = self.wire.get("push_rx_frames", 0)
            declared = self.declared_rx
        elif self.declared_tx > 0:
            measured = self.wire.get("push_tx_bytes", 0)
            frames = self.wire.get("push_tx_frames", 0)
            declared = self.declared_tx
        else:
            return False
        if not (declared <= measured
                <= declared + per_frame_bound * frames):
            return False
        if honesty_bound is None:
            from geomx_tpu.service.protocol import binary_wire_enabled
            if not binary_wire_enabled():
                return True
            honesty_bound = HONESTY_BOUND
        if frames > 0 and declared >= HONESTY_MIN_FRAME_PAYLOAD * frames:
            return measured <= honesty_bound * declared
        return True

    def snapshot(self) -> dict:
        return {
            "key": self.key, "round": self.round,
            "origin_party": self.origin_party,
            "status": self.status,
            "opened_unix": self.opened_unix,
            "closed_unix": self.closed_unix,
            "hops": [dict(h) for h in self.hops],
            "wire": dict(self.wire),
            "declared_tx_bytes": self.declared_tx,
            "declared_rx_bytes": self.declared_rx,
            "honesty_ratio": self.honesty_ratio(),
            "phases": dict(self.phases),
            "faults": len(self.fault_hops()),
            "detail": dict(self.detail),
        }


class RoundLedger:
    """Fold host-plane hop events into one record per (key, round).

    Thread-safe; every write is a dict hit plus one lock, cheap enough
    to ride the data path.  Completed records keep accepting late
    ``reply`` hops and byte accounting (pulls of a round legitimately
    arrive after its merge) until FIFO eviction."""

    def __init__(self, capacity: Optional[int] = None,
                 open_capacity: Optional[int] = None):
        self.capacity = _ledger_capacity() if capacity is None \
            else max(1, int(capacity))
        # open rounds are bounded too: a client-only process (no server
        # to complete its rounds) must not leak one record per push
        self.open_capacity = self.capacity if open_capacity is None \
            else max(1, int(open_capacity))
        self._lock = threading.Lock()
        self._open: "collections.OrderedDict[Tuple[str, int], RoundRecord]" \
            = collections.OrderedDict()
        self._done: "collections.OrderedDict[Tuple[str, int], RoundRecord]" \
            = collections.OrderedDict()
        self.completed_total = 0
        self.evicted_total = 0
        self.orphaned_total = 0
        self._evictions_published = 0
        # records closed under the lock, awaiting registry/event-log
        # publication OUTSIDE it (see _flush_publish): the ledger lock
        # is contended by every Msg.encode/decode, and a slow event-log
        # disk write must never stall the wire
        self._to_publish: List[RoundRecord] = []

    # ---- write side -------------------------------------------------------

    def _get_locked(self, key: str, round_id: int,
                    create: bool = True) -> Optional[RoundRecord]:
        rk = (str(key), int(round_id))
        rec = self._open.get(rk)
        if rec is None:
            rec = self._done.get(rk)
        if rec is None and create:
            rec = RoundRecord(*rk)
            self._open[rk] = rec
            while len(self._open) > self.open_capacity:
                _, old = self._open.popitem(last=False)
                self._close_locked(old, "orphaned",
                                   reason="open_capacity")
        return rec

    def record_hop(self, key: str, round_id: int, hop: str, *,
                   party: Optional[int] = None,
                   shard: Optional[int] = None,
                   t: Optional[float] = None,
                   dur_s: Optional[float] = None,
                   nbytes: Optional[int] = None,
                   detail: Optional[dict] = None) -> None:
        """Append one hop to the round's causal chain (sequence numbers
        are assigned here, so the chain is gapless by construction and
        ordered by arrival within this process).  ``reply``/``journal``
        hops never OPEN a record: they always follow a merge (or a
        push, client-side) — a straggler reply for a round already
        FIFO-evicted must not resurrect it as a fresh open record that
        nothing will ever complete."""
        if key is None or round_id is None:
            return
        ent: Dict[str, Any] = {"hop": str(hop),
                               "t": time.time() if t is None else float(t)}
        if party is not None:
            ent["party"] = int(party)
        if shard is not None:
            ent["shard"] = int(shard)
        if dur_s is not None:
            ent["dur_s"] = float(dur_s)
        if nbytes is not None:
            ent["nbytes"] = int(nbytes)
        if detail:
            ent["detail"] = dict(detail)
        with self._lock:
            rec = self._get_locked(key, round_id,
                                   create=hop not in (REPLY, JOURNAL))
            if rec is None:
                return
            ent["seq"] = len(rec.hops)
            rec.hops.append(ent)
            if rec.origin_party is None and party is not None \
                    and hop == PUSH:
                rec.origin_party = int(party)
        self._flush_publish()

    def add_phase(self, key: str, round_id: int, phase: str,
                  seconds: float) -> None:
        if key is None or round_id is None:
            return
        with self._lock:
            # phases always follow the merge/relay that opened the
            # record — never resurrect an evicted round
            rec = self._get_locked(key, round_id, create=False)
            if rec is None:
                return
            rec.phases[str(phase)] = \
                rec.phases.get(str(phase), 0.0) + float(seconds)

    def account_frame(self, direction: str, kind: str, key: str,
                      round_id: int, nbytes: int,
                      declared: Optional[int] = None) -> None:
        """One wire frame's bytes, attributed to (key, round).  Called
        from the ``Msg.encode`` (direction ``tx``) / ``Msg.decode``
        (``rx``) choke point — the one place every producer (including
        the pre-encoded priority-queue send paths) and every consumer
        meet, so the count is the frame that actually crossed (or will
        cross) the socket, length prefix included.  Only push frames
        may open a record; reply/relay bytes for an already-evicted
        round are dropped rather than resurrecting it."""
        kind = _WIRE_KINDS.get(kind, "other")
        with self._lock:
            rec = self._get_locked(key, round_id, create=kind == "push")
            if rec is None:
                return
            rec.wire[f"{kind}_{direction}_bytes"] += int(nbytes)
            rec.wire[f"{kind}_{direction}_frames"] += 1
            if declared is not None and kind == "push":
                if direction == "tx":
                    rec.declared_tx += int(declared)
                else:
                    rec.declared_rx += int(declared)
        self._flush_publish()

    # ---- completion / eviction -------------------------------------------

    def _close_locked(self, rec: RoundRecord, status: str,
                      reason: Optional[str] = None) -> None:
        rec.status = status
        rec.closed_unix = time.time()
        if reason:
            rec.detail["close_reason"] = reason
        self._done[(rec.key, rec.round)] = rec
        if status == "orphaned":
            self.orphaned_total += 1
        else:
            self.completed_total += 1
        while len(self._done) > self.capacity:
            self._done.popitem(last=False)
            self.evicted_total += 1
        # publication happens OUTSIDE the lock (_flush_publish): the
        # registry and the event log must never be touched while every
        # Msg.encode/decode in the process is parked on this lock
        self._to_publish.append(rec)

    def _flush_publish(self) -> None:
        """Publish any rounds closed since the last flush, outside the
        ledger lock.  Called at the end of every mutating public
        method; losing a race just means another caller publishes."""
        while True:
            with self._lock:
                if not self._to_publish:
                    return
                recs, self._to_publish = self._to_publish, []
                # the eviction delta is claimed under the lock so two
                # racing flushes can never double-publish it
                ev_delta = self.evicted_total - self._evictions_published
                self._evictions_published = self.evicted_total
            if ev_delta > 0:
                try:
                    from geomx_tpu.telemetry.registry import get_registry
                    get_registry().counter(
                        "geomx_ledger_evictions_total",
                        "Completed ledger records evicted FIFO past "
                        "GEOMX_LEDGER_ROUNDS").inc(ev_delta)
                except Exception:
                    pass
            for rec in recs:
                self._publish_close(rec)

    def _publish_close(self, rec: RoundRecord) -> None:
        """Registry + event-log fan-out for one closed round.  Resolved
        per call (like service/retry.count_retry) so test-time registry
        resets never orphan a cached child; best-effort by design.
        Runs WITHOUT the ledger lock."""
        try:
            from geomx_tpu.telemetry.registry import get_registry
            reg = get_registry()
            reg.counter(
                "geomx_ledger_rounds_total",
                "Ledger rounds closed", ("status",)).labels(
                status=rec.status).inc()
            reg.gauge(
                "geomx_ledger_open_rounds",
                "Ledger rounds currently open").set(len(self._open))
            ratio = rec.honesty_ratio()
            if ratio is not None:
                reg.gauge(
                    "geomx_wire_honesty_ratio",
                    "Latest per-round measured-vs-declared push byte "
                    "ratio").set(ratio)
            shard = next((h["shard"] for h in rec.hops
                          if h["hop"] == MERGE and "shard" in h), None)
            if rec.phases:
                fam = reg.histogram(
                    "geomx_round_phase_seconds",
                    "Per-round phase durations across the host plane",
                    ("shard", "phase"))
                for phase, secs in rec.phases.items():
                    fam.labels(shard=str(shard if shard is not None
                                         else -1),
                               phase=phase).observe(secs)
        except Exception:
            pass
        try:
            from geomx_tpu.telemetry.export import log_event
            log_event("round_ledger", key=rec.key, round=rec.round,
                      status=rec.status, hops=rec.hop_kinds(),
                      origin_party=rec.origin_party,
                      honesty_ratio=rec.honesty_ratio(),
                      wire=dict(rec.wire), phases=dict(rec.phases))
        except Exception:
            pass

    def complete(self, key: str, round_id: int) -> None:
        """The round's server-side lifecycle finished (merge + journal
        + first reply batch): move it to the completed ring.  Late
        reply hops / byte accounting still append (pulls of a round
        arrive after its merge) until eviction."""
        with self._lock:
            rec = self._open.pop((str(key), int(round_id)), None)
            if rec is not None:
                self._close_locked(rec, "complete")
        self._flush_publish()

    def complete_through(self, key: str, round_id: int) -> int:
        """Close every open round of ``key`` with round <= ``round_id``
        as complete — the CLIENT-side completion path: a pull reply's
        ``pushed`` proof says the server journaled those rounds, which
        is all a worker process (whose ledger never sees the server's
        merge) can ever learn.  Returns the number closed."""
        closed = 0
        with self._lock:
            victims = [rk for rk in self._open
                       if rk[0] == str(key) and rk[1] <= int(round_id)]
            for rk in victims:
                self._close_locked(self._open.pop(rk), "complete")
                closed += 1
        self._flush_publish()
        return closed

    def orphan(self, key: Optional[str] = None,
               round_id: Optional[int] = None,
               reason: str = "") -> int:
        """Close open rounds as ``status="orphaned"`` — a failed shard,
        a migrated key, an evicted sender whose rounds can never
        complete.  ``key=None`` matches every key; ``round_id=None``
        every round of the key.  Returns the number closed."""
        with self._lock:
            victims = [rk for rk in self._open
                       if (key is None or rk[0] == str(key))
                       and (round_id is None or rk[1] == int(round_id))]
            for rk in victims:
                self._close_locked(self._open.pop(rk), "orphaned",
                                   reason=reason or None)
        self._flush_publish()
        return len(victims)

    # ---- read side --------------------------------------------------------

    def get(self, key: str, round_id: int) -> Optional[dict]:
        with self._lock:
            rec = self._get_locked(key, round_id, create=False)
            return None if rec is None else rec.snapshot()

    def records(self, status: Optional[str] = None) -> List[dict]:
        """Snapshot every retained record, oldest first (open rounds
        last); optionally filtered by status."""
        with self._lock:
            out = [r.snapshot() for r in self._done.values()]
            out.extend(r.snapshot() for r in self._open.values())
        if status is not None:
            out = [r for r in out if r["status"] == status]
        return out

    def summary(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The scalars the FlightRecorder's ledger rules and the
        Pilot's sensors consume.  Deterministic for a given ``now``."""
        now = time.time() if now is None else float(now)
        with self._lock:
            oldest = None
            for rec in self._open.values():
                if oldest is None or rec.opened_unix < oldest.opened_unix:
                    oldest = rec
            ratios = [r for r in
                      (rec.honesty_ratio()
                       for rec in self._done.values()) if r is not None]
            out: Dict[str, Any] = {
                "ledger_open_rounds": len(self._open),
                "ledger_completed_total": self.completed_total,
                "ledger_orphaned_total": self.orphaned_total,
                "ledger_evicted_total": self.evicted_total,
                "ledger_open_round_age_s":
                    max(0.0, now - oldest.opened_unix)
                    if oldest is not None else 0.0,
            }
            if oldest is not None:
                out["ledger_oldest_open"] = (oldest.key, oldest.round)
            if ratios:
                out["wire_honesty_ratio"] = ratios[-1]
                out["wire_honesty_ratio_mean"] = sum(ratios) / len(ratios)
            return out

    def to_doc(self, label: Optional[str] = None) -> dict:
        """The ledger as a ``merge_traces``-compatible Chrome trace
        document: one complete "X" span per round (first hop -> close)
        plus one instant per hop, all carrying ``args.round_id`` /
        ``args.key`` — merged with the per-process profiler dumps, the
        Chrome timeline shows the full fleet round, hop by hop."""
        events: List[dict] = []
        recs = self.records()   # ONE snapshot for anchor + events
        anchor_us: Optional[float] = None
        for rec in recs:
            hops = rec["hops"]
            t0 = hops[0]["t"] if hops else rec["opened_unix"]
            if anchor_us is None or t0 * 1e6 < anchor_us:
                anchor_us = t0 * 1e6
        anchor_us = anchor_us if anchor_us is not None else 0.0
        for rec in recs:
            hops = rec["hops"]
            t0 = hops[0]["t"] if hops else rec["opened_unix"]
            t1 = rec["closed_unix"] or (hops[-1]["t"] if hops else t0)
            args = {"key": rec["key"], "round_id": rec["round"],
                    "status": rec["status"]}
            events.append({
                "name": f"LedgerRound:{rec['key']}", "cat": "ledger",
                "ph": "X", "pid": 0, "tid": 0,
                "ts": t0 * 1e6 - anchor_us,
                "dur": max(0.0, (t1 - t0) * 1e6), "args": args})
            for h in hops:
                events.append({
                    "name": f"LedgerHop:{h['hop']}", "cat": "ledger",
                    "ph": "i", "s": "t", "pid": 0,
                    "tid": h.get("party", 0),
                    "ts": h["t"] * 1e6 - anchor_us,
                    "args": {**args, "hop": h["hop"],
                             "seq": h["seq"],
                             "shard": h.get("shard")}})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": {"anchor_unix_us": anchor_us,
                             "ledger": True,
                             "label": label or "ledger"}}


# ---- process-global ledger (host plane writes, observatory reads) --------

_ledger: Optional[RoundLedger] = None
_ledger_lock = threading.Lock()


def get_round_ledger() -> RoundLedger:
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = RoundLedger()
        return _ledger


def reset_round_ledger(capacity: Optional[int] = None) -> RoundLedger:
    """Fresh global ledger (test isolation / bench runs)."""
    global _ledger
    with _ledger_lock:
        _ledger = RoundLedger(capacity=capacity)
        return _ledger


def account_frame(direction: str, kind: str, key: str, round_id: int,
                  nbytes: int, declared: Optional[int] = None) -> None:
    """Module-level forwarder the wire protocol calls (lazy, so the
    protocol module never imports telemetry at module scope and a
    test-time :func:`reset_round_ledger` takes effect immediately)."""
    get_round_ledger().account_frame(direction, kind, key, round_id,
                                     nbytes, declared=declared)


def record_hop(key: str, round_id: int, hop: str, **kw) -> None:
    """Module-level forwarder for hop producers (client/server/sharded
    call sites); same lazy-singleton contract as :func:`account_frame`."""
    get_round_ledger().record_hop(key, round_id, hop, **kw)


def add_phase(key: str, round_id: int, phase: str, seconds: float) -> None:
    get_round_ledger().add_phase(key, round_id, phase, seconds)


def complete_round(key: str, round_id: int) -> None:
    get_round_ledger().complete(key, round_id)


# ---------------------------------------------------------------------------
# per-request serving ledger (docs/serving.md): the RoundLedger traces
# gradient rounds; this traces inference requests through the gateway's
# causal chain — enqueue -> batch -> forward -> reply — with the same
# bounded-ring discipline, and summarizes p50/p99 per phase for the
# ``GET /ledger`` surface and the SLO policy's observation stream.
# ---------------------------------------------------------------------------

REQUEST_PHASES = ("queue", "forward", "reply")
DEFAULT_REQUESTS = 2048


def _request_capacity() -> int:
    from geomx_tpu.config import _env
    return max(1, _env(("GEOMX_LEDGER_REQUESTS",), DEFAULT_REQUESTS, int))


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    rank = max(0, min(len(sorted_vals) - 1,
                      int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[rank]


class RequestLedger:
    """Bounded FIFO ring of completed inference requests.

    One record per request: the wall-clock enqueue instant, the three
    phase durations (queue = enqueue->batch, forward = the jit'd batch
    dispatch this request rode, reply = result fan-out), the dispatched
    batch size and padded bucket, and the terminal status (``ok`` /
    ``shed`` / ``error``).  Writes are a deque append under one lock —
    cheap enough for the request path; reads snapshot."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = _request_capacity() if capacity is None \
            else max(1, int(capacity))
        self._lock = threading.Lock()
        self._records: "collections.deque" = \
            collections.deque(maxlen=self.capacity)
        self.observed_total = 0
        # byte-true wire accounting per transport lane ("native" /
        # "http"): actual on-wire bytes (frame length prefixes
        # included) vs the sender-declared payload bytes — the same
        # honesty discipline the RoundLedger applies to gradient
        # frames, here for inference traffic (docs/serving.md
        # "Serving fast path").
        self._wire: Dict[str, Dict[str, int]] = {}

    def observe(self, rid: int, *, t_enqueue: float, queue_s: float,
                forward_s: float, reply_s: float, batch_size: int,
                bucket: int, status: str = "ok",
                transport: Optional[str] = None,
                model_version: Optional[str] = None,
                model_round: Optional[int] = None,
                staleness_s: Optional[float] = None) -> None:
        rec = {"rid": int(rid), "t_enqueue": float(t_enqueue),
               "queue_s": float(queue_s), "forward_s": float(forward_s),
               "reply_s": float(reply_s),
               "total_s": float(queue_s) + float(forward_s)
               + float(reply_s),
               "batch_size": int(batch_size), "bucket": int(bucket),
               "status": str(status)}
        if transport is not None:
            rec["transport"] = str(transport)
        # freshness provenance (gateway dispatch stamps these from the
        # weight set the batch actually ran on); optional so non-serving
        # observers and old call sites stay untouched
        if model_version is not None:
            rec["model_version"] = str(model_version)
        if model_round is not None:
            rec["model_round"] = int(model_round)
        if staleness_s is not None:
            rec["staleness_s"] = float(staleness_s)
        with self._lock:
            self._records.append(rec)
            self.observed_total += 1

    def account_wire(self, transport: str, direction: str, nbytes: int,
                     declared: Optional[int] = None) -> None:
        """One inference frame's on-wire bytes (``direction`` is
        ``"rx"`` or ``"tx"``).  ``declared`` is what the sender claimed
        for the payload; actual/declared is the honesty ratio
        `summary()` reports — PER DIRECTION, because the two directions
        have structurally different payload sizes (a feature batch in,
        a logits row out): the ≤ 1.02 acceptance bound applies to the
        payload-dominant request direction, where frame overhead
        amortizes over real payload bytes, while a tiny reply payload
        under a fixed frame header is reported, not gated (no wire
        format can frame 80 bytes inside 2% overhead)."""
        with self._lock:
            lane = self._wire.setdefault(str(transport), {
                "rx_bytes": 0, "tx_bytes": 0, "frames": 0,
                "rx_declared": 0, "rx_declared_actual": 0,
                "tx_declared": 0, "tx_declared_actual": 0})
            lane[f"{direction}_bytes"] = \
                lane.get(f"{direction}_bytes", 0) + int(nbytes)
            lane["frames"] += 1
            if declared is not None and int(declared) > 0:
                lane[f"{direction}_declared"] = \
                    lane.get(f"{direction}_declared", 0) + int(declared)
                lane[f"{direction}_declared_actual"] = \
                    lane.get(f"{direction}_declared_actual", 0) \
                    + int(nbytes)

    def records(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._records]

    def summary(self) -> Dict[str, Any]:
        """p50/p99 per phase + end-to-end, status counts, and the
        retained window's sustained QPS (completed ``ok`` requests over
        the window's enqueue span)."""
        with self._lock:
            recs = list(self._records)
            total = self.observed_total
            wire = {t: dict(lane) for t, lane in self._wire.items()}
        out: Dict[str, Any] = {"requests": len(recs),
                               "observed_total": total}
        by_status: Dict[str, int] = {}
        by_transport: Dict[str, int] = {}
        for r in recs:
            by_status[r["status"]] = by_status.get(r["status"], 0) + 1
            t = r.get("transport")
            if t is not None:
                by_transport[t] = by_transport.get(t, 0) + 1
        out["by_status"] = by_status
        if by_transport:
            out["by_transport"] = by_transport
        if wire:
            for lane in wire.values():
                for d in ("rx", "tx"):
                    decl = lane.get(f"{d}_declared", 0)
                    lane[f"honesty_ratio_{d}"] = (
                        round(lane[f"{d}_declared_actual"] / decl, 4)
                        if decl > 0 else None)
            out["wire"] = wire
        ok = [r for r in recs if r["status"] == "ok"]
        for phase in REQUEST_PHASES + ("total",):
            vals = sorted(r[f"{phase}_s"] for r in ok)
            out[f"{phase}_p50_s"] = _percentile(vals, 0.50)
            out[f"{phase}_p99_s"] = _percentile(vals, 0.99)
        if len(ok) >= 2:
            span = max(r["t_enqueue"] for r in ok) \
                - min(r["t_enqueue"] for r in ok)
            out["qps"] = len(ok) / span if span > 0 else None
        else:
            out["qps"] = None
        if ok:
            out["batch_size_mean"] = \
                sum(r["batch_size"] for r in ok) / len(ok)
            out["batch_size_max"] = max(r["batch_size"] for r in ok)
        # freshness rollup over records carrying provenance — what the
        # gateway's dispatch stamped, so "staleness served" not
        # "staleness now"
        prov = [r for r in ok if "model_round" in r]
        if prov:
            out["freshness"] = {
                "records": len(prov),
                "model_round_min": min(r["model_round"] for r in prov),
                "model_round_max": max(r["model_round"] for r in prov),
                "staleness_max_s": max(
                    (r["staleness_s"] for r in prov
                     if "staleness_s" in r), default=None)}
        return out


_request_ledger: Optional[RequestLedger] = None
_request_ledger_lock = threading.Lock()


def get_request_ledger() -> RequestLedger:
    global _request_ledger
    with _request_ledger_lock:
        if _request_ledger is None:
            _request_ledger = RequestLedger()
        return _request_ledger


def peek_request_ledger() -> Optional[RequestLedger]:
    """The current request ledger WITHOUT creating one — the /ledger
    HTTP route's probe, so a pure-training process never grows a
    serving section."""
    with _request_ledger_lock:
        return _request_ledger


def reset_request_ledger(capacity: Optional[int] = None) -> RequestLedger:
    """Fresh global request ledger (test isolation / bench runs)."""
    global _request_ledger
    with _request_ledger_lock:
        _request_ledger = RequestLedger(capacity=capacity)
        return _request_ledger
