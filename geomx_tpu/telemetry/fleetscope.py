"""FleetScope: fleet-wide observability aggregation + freshness tracing.

Every observability surface the repo grew — the training plane's
RoundLedger + per-process ``/metrics``/``/healthz``/``/ledger``
(PR 13/14) and the serving plane's RequestLedger + replica watermarks
(PR 18/19) — is *per-process*: no component can answer "how healthy is
the fleet right now" or "how long does a gradient pushed by a party
take to influence an inference reply".  FleetScope is that component,
three pieces in one jax-free module (safe in the scheduler process):

- :class:`FleetScope` — a scheduler-colocated aggregator that discovers
  every node from the scheduler roster (``serve`` nodes registered by
  gateways/replicas poll over HTTP; any other role may opt in with an
  ``http=<port>`` tag field), polls ``/metrics`` (through the strict
  :func:`~geomx_tpu.telemetry.export.parse_prometheus_text`),
  ``/healthz`` and ``/ledger?summary=1`` on a bounded interval, and
  folds the results into ONE versioned fleet document.  Dead/stale
  nodes are *marked, never fatal*: a node that stops answering keeps
  its last-known entry with the links.py staleness idiom
  (``confidence = 2^(-age/stale_after_s)``, ``stale`` below 0.5) and a
  named reason, and every other node's fold is bit-identical to a fold
  without the failure (the degradation tests pin this);
- :class:`BurnRateMonitor` — a deterministic multi-window SLO burn-rate
  monitor: ``record(t, good, bad)`` appends to a bounded series and
  ``evaluate(now)`` is a pure fold over it — the same series evaluated
  at the same instants produces the same breach list, bit-identical
  (``bench.py --fleetscope`` gates this across two same-seed runs).  A
  breach onset emits a ``flight_anomaly`` event and bumps
  ``geomx_fleet_burn_breaches_total`` so SloPolicy and operators act
  on fleet truth, not gateway-local numbers;
- :class:`PropagationTracker` — the gradient-to-inference freshness
  join: training RoundLedger merge/journal hops → registry delta
  publish → replica apply → first request served on that round, one
  wall-clock instant per (round, stage), folded into per-round
  propagation latency (p50/p99) and exported as the
  ``geomx_fleet_propagation_seconds`` histogram.  The serve stage is
  recorded per transport, so the join proves freshness on BOTH
  inference doors.

Fleet rollups (QPS, shed rate, request p50/p99, honesty max, replica
staleness max, node health counts) publish as the
``geomx_fleet_rollup{field}`` gauge family — the surface
:class:`~geomx_tpu.control.sensors.ControlSensors` folds into every
:class:`~geomx_tpu.control.sensors.ControlObservation`.

``tools/gxtop.py`` renders the fleet document (snapshot / ``--watch`` /
``--json``); docs/telemetry.md "Fleetscope" documents the schema.
"""

from __future__ import annotations

import collections
import json
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

DEFAULT_INTERVAL_S = 2.0
DEFAULT_STALE_AFTER_S = 10.0
DEFAULT_BURN_WINDOWS = "60:14,300:6"
DEFAULT_SLO_TARGET = 0.99
DEFAULT_SLO_P99_S = 0.5
DEFAULT_PROPAGATION_ROUNDS = 512
DEFAULT_TRANSITIONS = 256

PROPAGATION_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                       10.0, 30.0)

# the propagation join's hop order: a round's latency is first-served
# minus the earliest training-side instant we know about (merge when
# the RoundLedger saw it, else the registry publish)
PROP_STAGES = ("merge", "publish", "apply", "served")


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (the
    RequestLedger's rule, duplicated so this module stays import-light)."""
    if not sorted_vals:
        return 0.0
    rank = max(0, min(len(sorted_vals) - 1,
                      int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[rank]


# ---------------------------------------------------------------------------
# propagation tracker: the gradient-to-inference freshness join
# ---------------------------------------------------------------------------

class PropagationTracker:
    """One record per training round: the wall-clock instants of its
    merge/publish/apply hops and the first request served on it (per
    transport).  Writes are a dict hit under one lock; FIFO-bounded at
    ``capacity`` rounds.  ``note`` keeps the EARLIEST instant per
    (round, stage) — replays and re-applies never move a watermark
    backward in time."""

    def __init__(self, capacity: int = DEFAULT_PROPAGATION_ROUNDS):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._rounds: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        self.noted_total = 0

    def note(self, round_id: int, stage: str, t: Optional[float] = None,
             transport: Optional[str] = None) -> None:
        if round_id is None or int(round_id) <= 0:
            return
        if stage not in PROP_STAGES:
            raise ValueError(f"unknown propagation stage {stage!r}")
        t = time.time() if t is None else float(t)
        served_fresh = False
        with self._lock:
            rec = self._rounds.get(int(round_id))
            if rec is None:
                rec = {"round": int(round_id), "served_by": {}}
                self._rounds[int(round_id)] = rec
                while len(self._rounds) > self.capacity:
                    self._rounds.popitem(last=False)
            if stage == "served":
                if "served" not in rec:
                    rec["served"] = t
                    served_fresh = True
                rec["served"] = min(rec["served"], t)
                if transport is not None:
                    lane = rec["served_by"]
                    lane[str(transport)] = min(
                        lane.get(str(transport), t), t)
            else:
                rec[stage] = min(rec.get(stage, t), t)
            self.noted_total += 1
            span = self._span(rec) if served_fresh else None
        if span is not None:
            self._publish_span(span)

    @staticmethod
    def _span(rec: dict) -> Optional[float]:
        """The round's propagation latency: first-served minus the
        earliest training-side instant (merge preferred, publish the
        fallback).  None until both ends exist."""
        if "served" not in rec:
            return None
        origin = rec.get("merge", rec.get("publish"))
        if origin is None:
            return None
        return max(0.0, rec["served"] - origin)

    def _publish_span(self, span: float) -> None:
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().histogram(
                "geomx_fleet_propagation_seconds",
                "Gradient-to-inference propagation latency per round "
                "(training merge/publish -> first request served)",
                buckets=PROPAGATION_BUCKETS).observe(float(span))
        except Exception:
            pass

    def rounds(self) -> List[dict]:
        with self._lock:
            out = []
            for rec in self._rounds.values():
                d = dict(rec)
                d["served_by"] = dict(rec["served_by"])
                span = self._span(rec)
                if span is not None:
                    d["propagation_s"] = span
                out.append(d)
            return out

    def ingest_round_records(self, records) -> int:
        """Fold RoundLedger record snapshots (``RoundLedger.records()``
        or a polled ``GET /ledger`` body's ``records``) into merge-stage
        notes: each record's earliest ``merge`` hop wall instant —
        ``journal`` as the fallback — anchors its round's join.
        Returns the number of rounds noted."""
        noted = 0
        for rec in records or ():
            try:
                round_id = int(rec.get("round", 0))
                hops = rec.get("hops") or ()
            except AttributeError:
                continue
            if round_id <= 0:
                continue
            best = None
            for hop in hops:
                if hop.get("hop") in ("merge", "journal") \
                        and "t" in hop:
                    t = float(hop["t"])
                    if best is None or t < best:
                        best = t
            if best is not None:
                self.note(round_id, "merge", t=best)
                noted += 1
        return noted

    def summary(self) -> Dict[str, Any]:
        """p50/p99 propagation over completed rounds + per-transport
        completion counts (the ``--fleetscope`` both-doors gate)."""
        recs = self.rounds()
        spans = sorted(r["propagation_s"] for r in recs
                       if "propagation_s" in r)
        by_transport: Dict[str, int] = {}
        for r in recs:
            if "propagation_s" not in r:
                continue
            for lane in r["served_by"]:
                by_transport[lane] = by_transport.get(lane, 0) + 1
        return {"rounds_tracked": len(recs),
                "rounds_completed": len(spans),
                "p50_s": _percentile(spans, 0.50),
                "p99_s": _percentile(spans, 0.99),
                "max_s": spans[-1] if spans else 0.0,
                "by_transport": by_transport}


_prop_tracker: Optional[PropagationTracker] = None
_prop_lock = threading.Lock()


def get_propagation_tracker() -> PropagationTracker:
    global _prop_tracker
    with _prop_lock:
        if _prop_tracker is None:
            _prop_tracker = PropagationTracker()
        return _prop_tracker


def reset_propagation_tracker(capacity: Optional[int] = None
                              ) -> PropagationTracker:
    """Fresh global tracker (test isolation / bench runs)."""
    global _prop_tracker
    with _prop_lock:
        _prop_tracker = PropagationTracker(
            capacity=capacity if capacity is not None
            else DEFAULT_PROPAGATION_ROUNDS)
        return _prop_tracker


def note_propagation(round_id: int, stage: str,
                     t: Optional[float] = None,
                     transport: Optional[str] = None) -> None:
    """Module-level forwarder the hop producers call (registry delta
    apply, replica apply, gateway serve) — lazy like the ledger's
    forwarders, and best-effort by design: freshness tracing must never
    take down the plane it traces."""
    try:
        get_propagation_tracker().note(round_id, stage, t=t,
                                       transport=transport)
    except ValueError:
        raise
    except Exception:
        pass


# ---------------------------------------------------------------------------
# deterministic multi-window SLO burn-rate monitor
# ---------------------------------------------------------------------------

def parse_burn_windows(spec: str) -> Tuple[Tuple[float, float], ...]:
    """``"60:14,300:6"`` -> ((60.0, 14.0), (300.0, 6.0)) — each pair is
    (window seconds, burn-rate threshold).  The multi-window AND rule
    (every window over its threshold) is the standard fast+slow pager
    pairing: the short window catches the spike, the long window proves
    it is not a blip."""
    out = []
    for part in (spec or DEFAULT_BURN_WINDOWS).split(","):
        part = part.strip()
        if not part:
            continue
        win, _, thr = part.partition(":")
        w, t = float(win), float(thr or 1.0)
        if w <= 0 or t <= 0:
            raise ValueError(f"bad burn window {part!r} in {spec!r}")
        out.append((w, t))
    if not out:
        raise ValueError(f"empty burn-window spec {spec!r}")
    return tuple(sorted(out))


class BurnRateMonitor:
    """Multi-window error-budget burn over a recorded (t, good, bad)
    series.  ``burn = bad_fraction / (1 - slo_target)``: burn 1.0
    consumes the budget exactly at the rate it refills; burn 14 over a
    60 s window eats an hour's budget in ~4 minutes.  A breach fires at
    the ONSET of every window simultaneously exceeding its threshold,
    and re-arms only after every window recovers — one event per
    episode, never a flap storm.

    Deterministic by construction: ``record`` stores explicit
    timestamps and ``evaluate(now)`` is a pure fold over the stored
    series — no clock is ever sampled inside the fold, so replaying the
    same series at the same instants yields a bit-identical breach list
    (the links.py/flight.py discipline)."""

    def __init__(self, windows=None, slo_target: float = DEFAULT_SLO_TARGET,
                 capacity: int = 4096):
        if isinstance(windows, str) or windows is None:
            windows = parse_burn_windows(windows or DEFAULT_BURN_WINDOWS)
        self.windows = tuple((float(w), float(t)) for w, t in windows)
        if not 0.0 < float(slo_target) < 1.0:
            raise ValueError(
                f"slo_target must be in (0, 1) (got {slo_target!r})")
        self.slo_target = float(slo_target)
        self.capacity = max(len(self.windows) + 1, int(capacity))
        self._series: "collections.deque" = \
            collections.deque(maxlen=self.capacity)
        self._breached = False
        self.breaches: List[dict] = []

    def record(self, t: float, good: float, bad: float) -> None:
        self._series.append((float(t), max(0.0, float(good)),
                             max(0.0, float(bad))))

    def burn_rates(self, now: float) -> List[dict]:
        """The pure per-window fold: bad fraction over the window's
        recorded ticks, scaled into budget-burn multiples."""
        now = float(now)
        out = []
        budget = 1.0 - self.slo_target
        for window_s, threshold in self.windows:
            good = bad = 0.0
            for t, g, b in self._series:
                if now - window_s < t <= now:
                    good += g
                    bad += b
            total = good + bad
            frac = (bad / total) if total > 0 else 0.0
            out.append({"window_s": window_s,
                        "threshold": threshold,
                        "good": good, "bad": bad,
                        "bad_fraction": frac,
                        "burn": frac / budget})
        return out

    def evaluate(self, now: float) -> Optional[dict]:
        """One deterministic tick: returns the breach dict at onset,
        None otherwise.  The onset emits ``flight_anomaly`` (rule
        ``fleet_burn_rate``) and bumps the breach counter best-effort —
        the returned/stored breach record itself is a pure function of
        the series, so determinism gates never see telemetry jitter."""
        rates = self.burn_rates(now)
        over = all(r["burn"] >= r["threshold"] and
                   (r["good"] + r["bad"]) > 0 for r in rates)
        if not over:
            if self._breached and all(
                    r["burn"] < r["threshold"] for r in rates):
                self._breached = False
            return None
        if self._breached:
            return None
        self._breached = True
        breach = {"rule": "fleet_burn_rate", "t": float(now),
                  "windows": rates,
                  "max_burn": max(r["burn"] for r in rates)}
        self.breaches.append(breach)
        try:
            from geomx_tpu.telemetry.export import log_event
            log_event("flight_anomaly", rule="fleet_burn_rate",
                      t=float(now), max_burn=breach["max_burn"],
                      windows=[(r["window_s"], round(r["burn"], 4))
                               for r in rates])
        except Exception:
            pass
        try:
            from geomx_tpu.telemetry.registry import get_registry
            get_registry().counter(
                "geomx_fleet_burn_breaches_total",
                "Fleet SLO burn-rate breach onsets").inc()
        except Exception:
            pass
        return breach

    def max_burn(self, now: float) -> float:
        rates = self.burn_rates(now)
        return max((r["burn"] for r in rates), default=0.0)


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------

def _default_fetch(url: str, timeout_s: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8")


def roster_targets(roster: Dict[str, list],
                   dead_ids=()) -> List[dict]:
    """Roster entries -> FleetScope node descriptors.  ``serve`` nodes
    registered their HTTP port directly (satellite: gateways/replicas
    register as node kind ``serve``); any other role opts into HTTP
    polling with an ``http=<port>`` field in its tag (fields are
    ``;``-separated).  Nodes with no HTTP surface are still tracked —
    their health comes from the scheduler's heartbeat dead list."""
    dead = {int(d) for d in dead_ids}
    out = []
    for role in sorted(roster):
        for entry in sorted(roster[role]):
            node_id, host, port = int(entry[0]), str(entry[1]), \
                int(entry[2])
            tag = str(entry[3]) if len(entry) > 3 else ""
            # port 0 = no HTTP surface (heartbeat-covered only), the
            # registry's binary-wire-only registration shape
            http_port = port if role == "serve" and port else None
            for field in tag.split(";"):
                if field.startswith("http="):
                    try:
                        http_port = int(field[5:])
                    except ValueError:
                        pass
            label = tag.split(";")[0] if tag else ""
            name = f"{role}:{label}" if label else f"{role}:{node_id}"
            out.append({"name": name, "kind": role, "id": node_id,
                        "host": host, "port": port,
                        "http_port": http_port,
                        "dead": node_id in dead})
    return out


class FleetScope:
    """The scheduler-colocated fleet aggregator.

    ``scheduler``: a :class:`~geomx_tpu.service.scheduler.GeoScheduler`
    to discover nodes from (roster + heartbeat dead list + its own
    metrics endpoint).  ``targets_fn``: the injectable alternative — a
    zero-arg callable returning node descriptor dicts (the
    :func:`roster_targets` shape); tests and the bench drive this.
    ``fetch_fn(url, timeout_s) -> text`` is injectable the same way, so
    the degradation tests can serve torn bodies and timeouts without a
    socket.  All polling state is per node-name; a fold is a pure
    function of (fetch results, dead list, ``now``), which is what
    makes the one-node-dies degradation bit-identical for every other
    node."""

    def __init__(self, scheduler=None,
                 targets_fn: Optional[Callable[[], List[dict]]] = None,
                 interval_s: Optional[float] = None,
                 stale_after_s: float = DEFAULT_STALE_AFTER_S,
                 burn_windows=None,
                 slo_target: float = DEFAULT_SLO_TARGET,
                 slo_p99_s: float = DEFAULT_SLO_P99_S,
                 timeout_s: float = 1.0,
                 fetch_fn: Optional[Callable[[str, float], str]] = None,
                 tracker: Optional[PropagationTracker] = None):
        if scheduler is None and targets_fn is None:
            raise ValueError("need a scheduler or a targets_fn")
        self.scheduler = scheduler
        self._targets_fn = targets_fn
        if interval_s is None:
            from geomx_tpu.config import _env
            interval_s = _env(("GEOMX_FLEETSCOPE_INTERVAL_S",),
                              DEFAULT_INTERVAL_S, float)
        self.interval_s = max(0.05, float(interval_s))
        if stale_after_s <= 0:
            raise ValueError(
                f"stale_after_s must be > 0 (got {stale_after_s!r})")
        self.stale_after_s = float(stale_after_s)
        if burn_windows is None:
            from geomx_tpu.config import _env
            burn_windows = _env(("GEOMX_FLEETSCOPE_BURN_WINDOWS",),
                                DEFAULT_BURN_WINDOWS, str)
        self.burn = BurnRateMonitor(windows=burn_windows,
                                    slo_target=slo_target)
        self.slo_p99_s = float(slo_p99_s)
        self.timeout_s = float(timeout_s)
        self._fetch = fetch_fn or _default_fetch
        self.tracker = tracker or get_propagation_tracker()
        self._lock = threading.Lock()
        self._doc: Optional[dict] = None
        self._fleet_version = 0
        # per-node poll state: last successful poll instant + last
        # successful bodies + last failure reason
        self._node_state: Dict[str, dict] = {}
        self._health: Dict[str, str] = {}
        self._request_counts: Dict[str, Dict[str, float]] = {}
        self.transitions: List[dict] = []
        self.polls_total = 0
        self.poll_errors_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- discovery ---------------------------------------------------------

    def targets(self) -> List[dict]:
        if self._targets_fn is not None:
            return list(self._targets_fn())
        sched = self.scheduler
        with sched._lock:
            roster = {r: list(v) for r, v in sched._roster.items()}
        dead = [] if sched.in_restart_grace() \
            else sched.heartbeats.dead_nodes()
        nodes = roster_targets(roster, dead_ids=dead)
        if sched.metrics_port:
            nodes.insert(0, {"name": "scheduler", "kind": "scheduler",
                             "id": -1, "host": "127.0.0.1",
                             "port": sched.metrics_port,
                             "http_port": sched.metrics_port,
                             "dead": False})
        return nodes

    # ---- one poll sweep ----------------------------------------------------

    def _poll_node(self, node: dict) -> Tuple[Optional[dict], Optional[str]]:
        """Fetch one node's three surfaces.  Returns (bodies, error):
        any torn body — an exposition the strict parser rejects, a
        /healthz that is not JSON, a timeout — yields a named error and
        NO partial bodies (a half-believed node would poison rollups)."""
        base = f"http://{node['host']}:{node['http_port']}"
        try:
            metrics = self._fetch(f"{base}/metrics", self.timeout_s)
            from geomx_tpu.telemetry.export import parse_prometheus_text
            families = parse_prometheus_text(metrics)
        except Exception as e:
            return None, f"metrics: {type(e).__name__}"
        try:
            healthz = json.loads(
                self._fetch(f"{base}/healthz", self.timeout_s))
        except Exception as e:
            return None, f"healthz: {type(e).__name__}"
        try:
            ledger = json.loads(
                self._fetch(f"{base}/ledger?summary=1", self.timeout_s))
        except Exception as e:
            return None, f"ledger: {type(e).__name__}"
        return {"families": families, "healthz": healthz,
                "ledger": ledger}, None

    @staticmethod
    def _counter_sum(families: dict, name: str,
                     label: Optional[str] = None,
                     value: Optional[str] = None) -> float:
        fam = families.get(name)
        if not fam:
            return 0.0
        total = 0.0
        for sname, labels, v in fam["samples"]:
            if sname != name:
                continue
            if label is not None and labels.get(label) != value:
                continue
            total += float(v)
        return total

    def poll_once(self, now: Optional[float] = None) -> dict:
        """One sweep + fold: poll every discoverable node, fold health
        and rollups, tick the burn monitor, version the document.
        ``now`` is injectable (virtual time in tests/bench) and is the
        only clock the fold reads."""
        now = time.time() if now is None else float(now)
        nodes = self.targets()
        entries: Dict[str, dict] = {}
        tick_good = tick_bad = 0.0
        rollup: Dict[str, Any] = {
            "qps": 0.0, "shed_rate": 0.0, "request_p50_s": 0.0,
            "request_p99_s": 0.0, "honesty_ratio_max": 0.0,
            "replica_staleness_max_s": 0.0, "propagation_p50_s": 0.0,
            "propagation_p99_s": 0.0}
        shed_num = shed_den = 0.0
        for node in nodes:
            name = node["name"]
            st = self._node_state.setdefault(
                name, {"last_ok": None, "bodies": None, "error": None})
            bodies = error = None
            if node.get("http_port") and not node.get("dead"):
                self.polls_total += 1
                bodies, error = self._poll_node(node)
                if bodies is not None:
                    st["last_ok"] = now
                    st["bodies"] = bodies
                    st["error"] = None
                else:
                    self.poll_errors_total += 1
                    st["error"] = error
            # ---- health: dead > stale > ok, reason always named -----
            if node.get("dead"):
                health, reason = "dead", "heartbeat_timeout"
                confidence = 0.0
            elif node.get("http_port") is None:
                # heartbeat-covered only: alive by the dead list
                health, reason, confidence = "ok", None, 1.0
            elif st["last_ok"] is None:
                health, reason = "stale", st["error"] or "never_polled"
                confidence = 0.0
            else:
                age = max(0.0, now - st["last_ok"])
                confidence = 2.0 ** (-age / self.stale_after_s)
                if confidence < 0.5:
                    health = "stale"
                    reason = st["error"] or "poll_age"
                else:
                    health, reason = "ok", None
            entry: Dict[str, Any] = {
                "kind": node["kind"], "id": node["id"],
                "host": node["host"], "port": node["port"],
                "http_port": node.get("http_port"),
                "health": health, "confidence": round(confidence, 4)}
            if reason is not None:
                entry["reason"] = reason
            if st["last_ok"] is not None:
                entry["age_s"] = round(max(0.0, now - st["last_ok"]), 3)
            # ---- fold the node's last-known surfaces ----------------
            known = st["bodies"]
            if known is not None:
                entry["healthz"] = known["healthz"]
                fams = known["families"]
                req = (known["ledger"].get("requests") or {}) \
                    .get("summary") or {}
                if isinstance(req.get("qps"), (int, float)) \
                        and health == "ok":
                    rollup["qps"] += float(req["qps"])
                for pk, rk in (("total_p50_s", "request_p50_s"),
                               ("total_p99_s", "request_p99_s")):
                    v = req.get(pk)
                    if isinstance(v, (int, float)):
                        rollup[rk] = max(rollup[rk], float(v))
                        entry[rk] = float(v)
                ok_n = self._counter_sum(
                    fams, "geomx_serve_requests_total", "status", "ok")
                bad_n = sum(self._counter_sum(
                    fams, "geomx_serve_requests_total", "status", s)
                    for s in ("shed", "error", "timeout"))
                shed_num += bad_n
                shed_den += ok_n + bad_n
                entry["requests"] = {"ok": ok_n, "bad": bad_n}
                honesty = self._counter_sum(
                    fams, "geomx_wire_honesty_ratio")
                rollup["honesty_ratio_max"] = max(
                    rollup["honesty_ratio_max"], honesty)
                serving = (known["healthz"] or {}).get("serving") or {}
                for prov in serving.values():
                    rep = prov.get("replica") if isinstance(prov, dict) \
                        else None
                    if isinstance(rep, dict) and isinstance(
                            rep.get("staleness_s"), (int, float)):
                        rollup["replica_staleness_max_s"] = max(
                            rollup["replica_staleness_max_s"],
                            float(rep["staleness_s"]))
                # burn inputs: this tick's request DELTAS per node; a
                # node whose p99 exceeds the latency SLO burns its ok
                # traffic too (slow is as bad as refused)
                if health == "ok":
                    prev = self._request_counts.get(name,
                                                    {"ok": 0.0,
                                                     "bad": 0.0})
                    d_ok = max(0.0, ok_n - prev["ok"])
                    d_bad = max(0.0, bad_n - prev["bad"])
                    p99 = req.get("total_p99_s")
                    if isinstance(p99, (int, float)) \
                            and float(p99) > self.slo_p99_s:
                        d_bad += d_ok
                        d_ok = 0.0
                    tick_good += d_ok
                    tick_bad += d_bad
                    self._request_counts[name] = {"ok": ok_n,
                                                  "bad": bad_n}
                # training-plane rounds: fold merge instants into the
                # propagation join when the node ships records
                recs = known["ledger"].get("records")
                if recs:
                    self.tracker.ingest_round_records(recs)
            entries[name] = entry
            # ---- health transitions, by name ------------------------
            prev_health = self._health.get(name)
            if prev_health is not None and prev_health != health:
                self.transitions.append(
                    {"node": name, "from": prev_health, "to": health,
                     "t": now, "reason": reason})
                del self.transitions[:-DEFAULT_TRANSITIONS]
            self._health[name] = health
        rollup["shed_rate"] = (shed_num / shed_den) if shed_den else 0.0
        prop = self.tracker.summary()
        rollup["propagation_p50_s"] = prop["p50_s"]
        rollup["propagation_p99_s"] = prop["p99_s"]
        counts = {"ok": 0, "stale": 0, "dead": 0}
        for e in entries.values():
            counts[e["health"]] += 1
        # ---- burn tick ------------------------------------------------
        self.burn.record(now, tick_good, tick_bad)
        breach = self.burn.evaluate(now)
        rollup["burn_rate_max"] = self.burn.max_burn(now)
        rollup["nodes_ok"] = counts["ok"]
        rollup["nodes_stale"] = counts["stale"]
        rollup["nodes_dead"] = counts["dead"]
        with self._lock:
            self._fleet_version += 1
            doc = {"kind": "geomx_fleet_document", "version": 1,
                   "fleet_version": self._fleet_version,
                   "now_unix": now,
                   "interval_s": self.interval_s,
                   "nodes": entries,
                   "rollups": rollup,
                   "burn": {
                       "windows": [{"window_s": w, "threshold": t}
                                   for w, t in self.burn.windows],
                       "slo_target": self.burn.slo_target,
                       "breached": self.burn._breached,
                       "breaches": [dict(b) for b in
                                    self.burn.breaches[-32:]]},
                   "propagation": prop,
                   "transitions": [dict(t) for t in
                                   self.transitions[-32:]]}
            if breach is not None:
                doc["breach"] = dict(breach)
            self._doc = doc
        self._publish_rollups(rollup)
        return doc

    def _publish_rollups(self, rollup: Dict[str, Any]) -> None:
        """The ControlSensors feed: every scalar rollup lands in the
        ``geomx_fleet_rollup{field}`` gauge family (first-label-keyed,
        the shape ``sensors._gauge_values`` reads)."""
        try:
            from geomx_tpu.telemetry.registry import get_registry
            fam = get_registry().gauge(
                "geomx_fleet_rollup",
                "FleetScope fleet-wide rollups, keyed by field",
                ("field",))
            for field, value in rollup.items():
                if isinstance(value, (int, float)):
                    fam.labels(field=field).set(float(value))
        except Exception:
            pass

    # ---- read side ---------------------------------------------------------

    def document(self) -> Optional[dict]:
        """The latest versioned fleet document (None before the first
        fold)."""
        with self._lock:
            return self._doc

    def document_route(self) -> Tuple[bytes, str]:
        """``GET /fleet`` body for the shared HTTP exporter."""
        doc = self.document()
        if doc is None:
            doc = {"kind": "geomx_fleet_document", "version": 1,
                   "fleet_version": 0, "nodes": {}}
        from geomx_tpu.telemetry.export import _json_default
        return (json.dumps(doc, default=_json_default).encode("utf-8"),
                "application/json")

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetScope":
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll_once()
                except Exception:
                    # a broken fold must never kill the aggregator —
                    # the next interval retries from clean state
                    self.poll_errors_total += 1
        self._thread = threading.Thread(target=run, name="fleetscope",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def fleetscope_from_config(scheduler) -> Optional[FleetScope]:
    """Construct (not start) a FleetScope from the environment knobs —
    ``GEOMX_FLEETSCOPE=1`` arms it; interval and burn windows come from
    ``GEOMX_FLEETSCOPE_INTERVAL_S`` / ``GEOMX_FLEETSCOPE_BURN_WINDOWS``.
    None when disabled (the default: zero threads, zero polls, and the
    traced train step untouched — the knobs are host-plane only, pinned
    by the jaxpr byte-identity test)."""
    from geomx_tpu.config import GeoConfig
    cfg = GeoConfig.from_env()
    if not cfg.fleetscope:
        return None
    return FleetScope(scheduler=scheduler,
                      interval_s=cfg.fleetscope_interval_s,
                      burn_windows=cfg.fleetscope_burn_windows)
