"""Roofline & MFU accounting from the compiled step program.

ROADMAP item 5 says MFU sits at ~0.17 and "the compute side, not the
wire, now bounds single-chip speed" — this module makes that kind of
claim *derivable from a running program* instead of a bench one-off:

- :func:`compiled_costs` reads model FLOPs and HBM bytes-accessed per
  step from XLA's ``compiled.cost_analysis()`` (the same source the
  bench's MFU column uses);
- :func:`roofline_record` grades the measured step time against the
  three rooflines that can bound it — peak compute, memory bandwidth,
  and the WAN wire (bytes from ``sync.wire_accounting``) — and emits a
  verdict naming the binding resource, in the wire/compute-balance
  spirit of EQuARX (PAPERS.md);
- :func:`publish_roofline` exports the numbers as registry gauges so
  the scheduler's ``/metrics`` surface serves live MFU.

The verdict is the sensor the self-tuning controller (ROADMAP item 3)
and the MFU-raising work (item 5) both consume: "wire_bound" means
compression/pipelining has headroom to buy, "compute_bound" means it
does not and the kernels are the lever.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

# peak dense bf16 FLOP/s per chip by device_kind substring (public
# specs; the bench's table, owned here so both read one source)
PEAK_BF16 = (
    ("v6", 918e12),        # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),        # v5e reports "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)

# published HBM bandwidth per chip, bytes/s (same substring match)
HBM_BYTES_PER_S = (
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5", 819e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def _lookup(table, device_kind: str) -> Optional[float]:
    dk = (device_kind or "").lower()
    for sub, val in table:
        if sub in dk:
            return val
    return None


def peak_flops(device_kind: str) -> Optional[float]:
    return _lookup(PEAK_BF16, device_kind)


def peak_hbm_bytes_per_s(device_kind: str) -> Optional[float]:
    return _lookup(HBM_BYTES_PER_S, device_kind)


def compiled_costs(compiled) -> Dict[str, Any]:
    """FLOPs and bytes-accessed per execution from a compiled program's
    ``cost_analysis()``; ``{"available": False}`` where the backend
    offers none (some CPU jaxlibs)."""
    try:
        ca = compiled.cost_analysis()
    except Exception as e:
        return {"available": False, "error": repr(e)}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not ca:
        return {"available": False}
    out: Dict[str, Any] = {"available": True}
    flops = float(ca.get("flops", 0.0) or 0.0)
    out["flops"] = flops if flops > 0 else None
    byt = float(ca.get("bytes accessed", 0.0) or 0.0)
    out["bytes_accessed"] = byt if byt > 0 else None
    return out


def calibrate_peak_flops(n: int = 512, reps: int = 3) -> float:
    """Measured matmul FLOP/s on the current default backend — the
    *effective* peak where no published number exists (host CPU).  An
    MFU against this calibration reads as "fraction of what this
    machine's best dense kernel achieves", which is the honest CPU
    analogue of the TPU spec number."""
    import time

    import jax
    import jax.numpy as jnp

    a = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda x: x @ x)
    f(a).block_until_ready()  # compile
    best = math.inf
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        f(a).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return (2.0 * n ** 3) / best


def roofline_record(*, flops: Optional[float],
                    step_time_s: float,
                    peak_flops_per_s: Optional[float],
                    hbm_bytes: Optional[float] = None,
                    hbm_bytes_per_s: Optional[float] = None,
                    wire_bytes: Optional[float] = None,
                    wire_bytes_per_s: Optional[float] = None
                    ) -> Dict[str, Any]:
    """Grade one step against the three rooflines.

    Per-resource lower-bound times are ``t_compute = flops/peak``,
    ``t_memory = hbm_bytes/hbm_bw``, ``t_wire = wire_bytes/wire_bw``
    (each only when both numerator and rate are known); the verdict
    names the largest — the resource whose roofline the measured step
    cannot beat.  ``mfu`` is achieved FLOP/s over peak,
    ``arithmetic_intensity`` is FLOPs per HBM byte, and
    ``ridge_flops_per_byte`` (peak/bw) locates the measured intensity
    on the classic roofline: below the ridge the memory roof is the
    binding one at full utilization.
    """
    if step_time_s <= 0:
        raise ValueError(f"step_time_s must be > 0 (got {step_time_s!r})")
    rec: Dict[str, Any] = {
        "flops_per_step": flops, "step_time_s": step_time_s,
        "peak_flops_per_s": peak_flops_per_s,
        "hbm_bytes_per_step": hbm_bytes,
        "wire_bytes_per_step": wire_bytes,
    }
    achieved = (flops / step_time_s) if flops else None
    rec["achieved_flops_per_s"] = achieved
    rec["mfu"] = (achieved / peak_flops_per_s
                  if achieved and peak_flops_per_s else None)
    rec["arithmetic_intensity"] = (flops / hbm_bytes
                                   if flops and hbm_bytes else None)
    rec["ridge_flops_per_byte"] = (
        peak_flops_per_s / hbm_bytes_per_s
        if peak_flops_per_s and hbm_bytes_per_s else None)

    bounds: Dict[str, float] = {}
    if flops and peak_flops_per_s:
        bounds["compute"] = flops / peak_flops_per_s
    if hbm_bytes and hbm_bytes_per_s:
        bounds["memory"] = hbm_bytes / hbm_bytes_per_s
    if wire_bytes and wire_bytes_per_s:
        bounds["wire"] = wire_bytes / wire_bytes_per_s
    rec["bound_times_s"] = bounds
    if bounds:
        verdict = max(bounds, key=lambda k: bounds[k])
        rec["bound"] = f"{verdict}_bound"
        ordered = sorted(bounds.values(), reverse=True)
        # dominance of the verdict over the runner-up: 1.0 = ties, big =
        # unambiguous.  With one resource known there is no runner-up.
        rec["bound_dominance"] = (ordered[0] / ordered[1]
                                  if len(ordered) > 1 and ordered[1] > 0
                                  else None)
        # fraction of the measured step the binding resource explains —
        # <1 always (the roofline is a lower bound); near 1 means the
        # step runs at that roofline, small means overhead elsewhere
        rec["bound_explains_fraction"] = min(
            bounds[verdict] / step_time_s, 1.0)
    else:
        rec["bound"] = "unknown"
        rec["bound_dominance"] = None
        rec["bound_explains_fraction"] = None
    return rec


def publish_roofline(rec: Dict[str, Any], registry=None) -> None:
    """Export a roofline record as registry gauges: ``geomx_mfu``,
    ``geomx_arithmetic_intensity``, ``geomx_roofline_bound{bound=...}``
    (one-hot over the three verdicts) and the per-resource lower-bound
    times ``geomx_roofline_bound_seconds{resource=...}``."""
    from geomx_tpu.telemetry.registry import get_registry
    reg = registry if registry is not None else get_registry()
    if rec.get("mfu") is not None:
        reg.gauge("geomx_mfu",
                  "Model FLOPs utilization of the measured step").set(
            float(rec["mfu"]))
    if rec.get("arithmetic_intensity") is not None:
        reg.gauge("geomx_arithmetic_intensity",
                  "Step FLOPs per HBM byte accessed").set(
            float(rec["arithmetic_intensity"]))
    fam = reg.gauge("geomx_roofline_bound",
                    "1 on the resource verdict bounding the step",
                    ("bound",))
    for b in ("compute_bound", "memory_bound", "wire_bound"):
        fam.labels(bound=b).set(1.0 if rec.get("bound") == b else 0.0)
    fam_t = reg.gauge("geomx_roofline_bound_seconds",
                      "Per-resource roofline lower bound on step time",
                      ("resource",))
    for res, t in (rec.get("bound_times_s") or {}).items():
        fam_t.labels(resource=res).set(float(t))


def trainer_roofline(trainer, state, xb, yb, step_time_s: float,
                     device_kind: Optional[str] = None,
                     wire_seconds: Optional[float] = None
                     ) -> Dict[str, Any]:
    """Roofline record for a live trainer: FLOPs/bytes from the compiled
    step, wire bytes from the sync algorithm's static accounting, peaks
    from the device table (or a CPU calibration when the table has no
    row).  ``wire_seconds``: measured/injected per-step WAN time — when
    given, the wire roofline uses the *achieved* rate
    (wire_bytes/wire_seconds) so the verdict reflects the link actually
    in use."""
    import jax

    compiled = trainer.train_step.lower(state, xb, yb).compile()
    costs = compiled_costs(compiled)
    if device_kind is None:
        device_kind = getattr(jax.devices()[0], "device_kind", "")
    peak = peak_flops(device_kind)
    hbm_bw = peak_hbm_bytes_per_s(device_kind)
    calibrated = False
    if peak is None:
        peak = calibrate_peak_flops()
        calibrated = True
    params = jax.tree.map(lambda a: a[0, 0], state.params)
    wire = float((trainer.sync.wire_accounting(params) or {}).get(
        "dc_wire_bytes", 0.0)) or None
    wire_bw = (wire / wire_seconds
               if wire and wire_seconds and wire_seconds > 0 else None)
    rec = roofline_record(
        flops=costs.get("flops"), step_time_s=step_time_s,
        peak_flops_per_s=peak, hbm_bytes=costs.get("bytes_accessed"),
        hbm_bytes_per_s=hbm_bw, wire_bytes=wire,
        wire_bytes_per_s=wire_bw)
    rec["device_kind"] = device_kind
    rec["peak_calibrated"] = calibrated
    rec["cost_analysis_available"] = costs.get("available", False)
    return rec
