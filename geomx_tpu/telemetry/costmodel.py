"""Fitted step-time cost model over run-capsule records.

ROADMAP item 5's planner needs an *oracle*: "what would step time be
under config C on the links this run actually had?" — the trade-off
study of "Evaluation and Optimization of Gradient Compression for
Distributed Deep Learning" (PAPERS.md), which fits communication cost
curves from measured runs, and EQuARX, which publishes measured
quantized-collective cost curves for exactly this purpose.
:class:`StepTimeCostModel` is that oracle, fitted from ONE
:class:`~geomx_tpu.telemetry.capsule.Capsule`:

- **links**: per-party uplink models ``seconds(B) = a + B*ib``.  When
  the run fed *paired* observations — the payload transfer on the
  ``global`` peer plus a heartbeat-sized probe on the ``probe`` peer
  (what ``bench.py --compare-capsule`` records; the scheduler's
  heartbeats are the live analogue) — the pair solves ``(a, ib)``
  EXACTLY per step, so latency shaping and bandwidth shaping separate
  and the model tracks chaos windows step by step.  Without probes it
  falls back to a least-squares affine fit over the journal plus a
  per-observation multiplicative residual — exact at the capsule's
  own payload sizes, interpolated elsewhere;
- **compute**: the median per-step compute seconds from the capsule's
  step records (``timing.compute_s``, or the compute phase fraction
  times total step seconds);
- **structure**: the same overlap semantics the system implements —
  a synchronous dc tier exposes the whole WAN round; pipeline depth
  >= 1 hides ``min(wan, compute)`` behind the next step's compute
  (sync/pipeline.py), so ``step = compute + max(0, wan - compute)``.

:meth:`predict` takes a candidate ``(compression, depth,
bucket_bytes)`` config, derives its per-step wire bytes from the
capsule's recorded parameter layout via the compressors' own static
wire accounting (:func:`candidate_wire_bytes` — the same
``wire_bytes`` the GX-DTYPE-002 audit holds honest), and integrates
the per-step prediction over the capsule's timeline.  ``bench.py
--compare-capsule`` validates the model's *ranking* of a ratio x
depth x compressor grid against measured runs and reports per-config
relative error (docs/performance.md "What-if search over capsules").

Known limits (documented, not hidden): compute is treated as
config-invariant (a candidate whose compressor changes on-chip time —
PR 12's whole point — inherits the capsule's measured compute), and
the residual correction is exact only at the capsule's own payload
sizes; between them the affine interpolation rules.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

DEFAULT_PEER = "global"
PROBE_PEER = "probe"


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        raise ValueError("median of empty sequence")
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def fit_affine_link(samples: List[dict]) -> Dict[str, Any]:
    """Least-squares affine fit ``seconds = latency + bytes *
    sec_per_byte`` over one party's journal samples, clamped to the
    physical region (latency >= 0, sec_per_byte > 0; a degenerate
    spread falls back to the zero-latency throughput line).  Each
    sample gains ``resid`` — measured over fitted — so predictions can
    re-apply the run's time-local conditions."""
    pts = [(float(s["nbytes"]), float(s["seconds"]), float(s["t"]))
           for s in samples
           if s.get("ok", True) and s.get("seconds")
           and float(s.get("nbytes") or 0) > 0]
    if not pts:
        raise ValueError("no usable (bytes, seconds) samples to fit")
    n = len(pts)
    sum_b = sum(b for b, _s, _t in pts)
    sum_s = sum(s for _b, s, _t in pts)
    sum_bb = sum(b * b for b, _s, _t in pts)
    sum_bs = sum(b * s for b, s, _t in pts)
    den = n * sum_bb - sum_b * sum_b
    if den > 0:
        ib = (n * sum_bs - sum_b * sum_s) / den
        a = (sum_s - ib * sum_b) / n
    else:                       # one distinct payload size: slope-only
        ib, a = -1.0, 0.0
    if ib <= 0:                 # unphysical: zero-latency throughput line
        ib = sum_s / sum_b
        a = 0.0
    elif a < 0:                 # re-fit the slope through the origin
        ib = sum_bs / sum_bb
        a = 0.0
    fitted_samples = []
    for b, s, t in pts:
        nominal = a + b * ib
        fitted_samples.append({
            "t": t, "nbytes": b, "seconds": s,
            "resid": s / nominal if nominal > 0 else 1.0})
    return {"latency_s": a, "sec_per_byte": ib,
            "num_samples": n, "samples": fitted_samples}


def fit_paired_link(payload: List[dict],
                    probe: List[dict]) -> Optional[Dict[str, Any]]:
    """EXACT per-step link solve from paired observations: at each run
    clock ``t`` with both a payload transfer (bytes ``Bg``, seconds
    ``sg``) and a probe (``Bp``, ``sp``),

        sec_per_byte = (sg - sp) / (Bg - Bp),
        latency_s    = sp - Bp * sec_per_byte,

    clamped to the physical region.  Returns a per-``t`` timeline of
    ``(latency_s, sec_per_byte)`` plus median summary params, or None
    when fewer than one pair matched (the caller falls back to the
    affine fit)."""
    by_t = {float(s["t"]): s for s in probe
            if s.get("ok", True) and s.get("seconds")}
    timeline: List[dict] = []
    for s in payload:
        if not (s.get("ok", True) and s.get("seconds")):
            continue
        p = by_t.get(float(s["t"]))
        if p is None:
            continue
        bg, sg = float(s["nbytes"]), float(s["seconds"])
        bp, sp = float(p["nbytes"]), float(p["seconds"])
        if bg <= bp:
            continue
        ib = (sg - sp) / (bg - bp)
        if ib <= 0:
            ib = sg / bg
            a = 0.0
        else:
            a = max(0.0, sp - bp * ib)
        timeline.append({"t": float(s["t"]), "latency_s": a,
                         "sec_per_byte": ib})
    if not timeline:
        return None
    timeline.sort(key=lambda e: e["t"])
    return {
        "latency_s": _median([e["latency_s"] for e in timeline]),
        "sec_per_byte": _median([e["sec_per_byte"] for e in timeline]),
        "num_samples": len(timeline),
        "timeline": timeline,
    }


def candidate_wire_bytes(param_shapes: Dict[str, dict],
                         compression: str,
                         bucket_bytes: int) -> float:
    """Per-party per-step dc-tier wire bytes for a candidate config,
    from the compressors' own static accounting over the capsule's
    recorded parameter layout (``manifest["param_shapes"]``).  Imports
    jax lazily — the capsule/ledger read path stays jax-free."""
    import jax

    from geomx_tpu.compression.base import get_compressor
    from geomx_tpu.compression.bucketing import BucketedCompressor
    tree = {name: jax.ShapeDtypeStruct(tuple(meta["shape"]),
                                       meta["dtype"])
            for name, meta in param_shapes.items()}
    comp = get_compressor(compression)
    if bucket_bytes:
        comp = BucketedCompressor(comp, bucket_bytes=int(bucket_bytes))
    return float(comp.wire_bytes(tree))


class StepTimeCostModel:
    """The fitted oracle: per-party affine+residual link models, a
    compute constant, and the capsule's step timeline to integrate
    predictions over."""

    def __init__(self, links: Dict[str, dict], compute_s: float,
                 step_times: List[float],
                 param_shapes: Optional[Dict[str, dict]] = None,
                 peer: str = DEFAULT_PEER,
                 skipped_links: Optional[List[str]] = None):
        if not links:
            raise ValueError("cost model needs at least one fitted link")
        self.links = links
        self.compute_s = float(compute_s)
        self.step_times = list(step_times)   # the capsule's step clocks
        self.param_shapes = param_shapes
        self.peer = peer
        # parties whose journal had no usable timing (a link dead for
        # the whole run): predictions cover the fitted parties only
        self.skipped_links = list(skipped_links or [])

    # ---- fitting -----------------------------------------------------------

    @classmethod
    def fit(cls, capsule, peer: str = DEFAULT_PEER,
            probe_peer: str = PROBE_PEER) -> "StepTimeCostModel":
        """Fit from one loaded :class:`Capsule`: links from the link
        journal (exact per-step pairs when ``probe_peer`` observations
        exist, affine+residual otherwise), compute from the step
        records' timing."""
        by_party: Dict[str, List[dict]] = {}
        probes: Dict[str, List[dict]] = {}
        for e in capsule.link_journal:
            if e.get("peer") == peer:
                by_party.setdefault(e["party"], []).append(e)
            elif e.get("peer") == probe_peer:
                probes.setdefault(e["party"], []).append(e)
        links: Dict[str, dict] = {}
        skipped: List[str] = []
        for p, samples in sorted(by_party.items()):
            fit = fit_paired_link(samples, probes.get(p, []))
            if fit is None:
                try:
                    fit = fit_affine_link(samples)
                except ValueError:
                    # a party whose every observation failed (a link
                    # dead for the whole run) has no timing to fit —
                    # model the parties that do, and say so
                    skipped.append(p)
                    continue
            links[p] = fit
        compute_samples: List[float] = []
        step_times: List[float] = []
        for rec in capsule.steps:
            step_times.append(float(rec["t"]))
            timing = rec.get("timing") or {}
            if "compute_s" in timing:
                compute_samples.append(float(timing["compute_s"]))
            elif "total_s" in timing and rec.get("phases", {}) \
                    .get("compute") is not None:
                compute_samples.append(float(timing["total_s"])
                                       * float(rec["phases"]["compute"]))
        if not compute_samples:
            raise ValueError(
                "capsule has no per-step compute timing (record_step "
                "timing= or phases.compute + timing.total_s)")
        return cls(links, _median(compute_samples), step_times,
                   param_shapes=capsule.manifest.get("param_shapes"),
                   peer=peer, skipped_links=skipped)

    # ---- prediction --------------------------------------------------------

    def _uplink_at(self, party: str, nbytes: float,
                   t: Optional[float]) -> float:
        """Predicted uplink seconds for ``nbytes`` on ``party`` at run
        clock ``t`` — the link state the run measured then: the exact
        per-step ``(latency, sec_per_byte)`` pair when the fit had
        probes, else the affine nominal scaled by the residual of the
        latest journal observation at or before ``t``."""
        fit = self.links[party]
        timeline = fit.get("timeline")
        if timeline:
            entry = timeline[0]
            if t is not None:
                for e in timeline:
                    if e["t"] <= t:
                        entry = e
                    else:
                        break
            else:
                entry = {"latency_s": fit["latency_s"],
                         "sec_per_byte": fit["sec_per_byte"]}
            return entry["latency_s"] + nbytes * entry["sec_per_byte"]
        nominal = fit["latency_s"] + nbytes * fit["sec_per_byte"]
        resid = 1.0
        if t is not None:
            for s in fit["samples"]:
                if s["t"] <= t:
                    resid = s["resid"]
                else:
                    break
        return resid * nominal

    def wan_round_s(self, nbytes: float,
                    t: Optional[float] = None) -> float:
        """One synchronous WAN round at run clock ``t``: the gate waits
        for the slowest party's uplink (direct fan-in — the shape the
        static grid configs run)."""
        return max(self._uplink_at(p, nbytes, t) for p in self.links)

    def predict_step_s(self, nbytes: float, depth: int,
                       t: Optional[float] = None) -> Dict[str, float]:
        wan = self.wan_round_s(nbytes, t)
        hidden = min(wan, self.compute_s) if depth else 0.0
        exposed = wan - hidden
        return {"total": self.compute_s + exposed, "wan": wan,
                "exposed": exposed, "hidden": hidden}

    def predict(self, candidate: Dict[str, Any],
                param_shapes: Optional[Dict[str, dict]] = None
                ) -> Dict[str, Any]:
        """Predict mean step time for a candidate config dict:
        ``compression`` (spec string), ``depth`` (0/1), ``bucket_bytes``
        (0 = per-leaf), optional ``emitted_fraction`` (a controller's
        achieved emission; static configs send capacity = 1.0) or an
        explicit ``wire_bytes`` override.  Integrated over the
        capsule's step timeline so chaos windows price in at the steps
        they actually covered."""
        shapes = param_shapes or self.param_shapes
        if "wire_bytes" in candidate:
            nbytes = float(candidate["wire_bytes"])
        else:
            if not shapes:
                raise ValueError(
                    "candidate has no wire_bytes and the capsule "
                    "recorded no param_shapes")
            nbytes = candidate_wire_bytes(
                shapes, candidate.get("compression", "none"),
                candidate.get("bucket_bytes", 0))
        nbytes *= float(candidate.get("emitted_fraction", 1.0))
        depth = int(candidate.get("depth", 0))
        times = self.step_times or [None]
        per_step = [self.predict_step_s(nbytes, depth, t)["total"]
                    for t in times]
        return {
            "wire_bytes": nbytes,
            "depth": depth,
            "mean_step_s": sum(per_step) / len(per_step),
            "num_steps": len(per_step),
        }

    def to_json(self) -> dict:
        """JSON form (bench artifact / docs examples) — fits without
        the per-sample residual tables."""
        out = {
            "compute_s": self.compute_s,
            "links": {p: {k: f[k] for k in
                          ("latency_s", "sec_per_byte", "num_samples")}
                      for p, f in sorted(self.links.items())},
            "num_steps": len(self.step_times),
        }
        if self.skipped_links:
            out["skipped_links"] = self.skipped_links
        return out
