"""Flight recorder: a bounded ring of per-step records + anomaly dumps.

The telemetry plane publishes the *latest* probe values; when a run
goes wrong (a party's gradient turns NaN at step 48 012, achieved
density quietly drifts, the exposed-comms fraction jumps after a link
degrades) the question is always "what did the last few hundred steps
look like" — and by the time anyone asks, the registry only remembers
the end state.  :class:`FlightRecorder` keeps the answer in memory:

- a ring of the last K per-step records (probe values, phase
  breakdown, membership epoch, wire bytes — whatever the trainer
  publishes), bounded at ``GEOMX_FLIGHT_STEPS`` records;
- deterministic anomaly rules evaluated on every record against the
  ring's rolling history: a **nonfinite probe** (including the
  per-party vector, so the bundle names the poisoned party the
  aggregate hides), a **grad-norm spike** vs the rolling median, an
  **achieved-density drift**, and an **exposed-comms fraction jump**;
- when a rule fires, the whole ring dumps as one JSON forensics
  bundle (ATOMIC, via the same temp-file+replace the profiler uses) —
  the flight recorder's black-box readout.

Everything is pure functions of the recorded values: the same step
sequence fires the same rules at the same steps, which is what makes a
seeded NaN injection a deterministic acceptance test.

Gated by ``GEOMX_FLIGHT`` / ``GeoConfig(flight=True)``; requires the
telemetry probes (no probes, nothing to record — the trainer warns).
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

DEFAULT_STEPS = 256

# anomaly rule ids (the bundle's "fired" entries carry these)
NONFINITE = "nonfinite_probe"
GRAD_SPIKE = "grad_norm_spike"
DENSITY_DRIFT = "density_drift"
EXPOSED_JUMP = "exposed_comms_jump"
STUCK_ROUND = "stuck_round"
HONESTY_DRIFT = "honesty_ratio_drift"


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return math.nan
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _finite(vals) -> List[float]:
    return [v for v in vals if v is not None and math.isfinite(v)]


class FlightRecorder:
    """Bounded per-step record ring with anomaly-triggered dumps.

    ``capacity``: ring size (``GEOMX_FLIGHT_STEPS``).  ``dump_dir``:
    where forensics bundles land ("" disables auto-dump; rules still
    evaluate and report).  Rule knobs (all overridable per instance,
    env rows in docs/telemetry.md):

    - ``spike_factor``: grad-norm spike fires when the norm exceeds
      this multiple of the rolling median (GEOMX_FLIGHT_SPIKE);
    - ``density_drift``: achieved-density drift fires when
      ``dc_nonzero_fraction`` moves more than this *relative* fraction
      away from the rolling median (GEOMX_FLIGHT_DENSITY_DRIFT);
    - ``exposed_jump``: exposed-comms fires when the fraction exceeds
      the rolling median by this *absolute* amount
      (GEOMX_FLIGHT_EXPOSED_JUMP);
    - ``stuck_round_s``: the fleet-round-ledger rule — fires when the
      oldest OPEN round (``ledger_open_round_age_s``, fed by
      :meth:`record_ledger`) has been open longer than this
      (GEOMX_FLIGHT_STUCK_S);
    - ``honesty_drift``: fires when the per-round wire honesty ratio
      (``wire_honesty_ratio``) moves more than this *relative*
      fraction away from its rolling median — framing/retry overhead
      quietly growing, or a compressor starting to lie
      (GEOMX_FLIGHT_HONESTY_DRIFT);
    - ``min_history``: rolling rules stay quiet until this many prior
      records exist (a fresh run's first steps are not anomalies);
    - ``window``: how many trailing records feed the rolling median.
    """

    def __init__(self, capacity: int = DEFAULT_STEPS,
                 dump_dir: str = "",
                 spike_factor: float = 10.0,
                 density_drift: float = 0.5,
                 exposed_jump: float = 0.25,
                 stuck_round_s: float = 30.0,
                 honesty_drift: float = 0.25,
                 min_history: int = 5,
                 window: int = 64,
                 decision_capacity: int = 64,
                 incident_capacity: int = 128):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0 (got {capacity!r})")
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.spike_factor = float(spike_factor)
        self.density_drift = float(density_drift)
        self.exposed_jump = float(exposed_jump)
        self.stuck_round_s = float(stuck_round_s)
        self.honesty_drift = float(honesty_drift)
        self.min_history = int(min_history)
        self.window = int(window)
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity)
        # controller actuations (control/actuators.py): a bounded
        # sibling ring so a forensics bundle shows the last N decisions
        # alongside the step records — "the density drifted at step 412"
        # reads very differently next to "the pilot lowered the ratio at
        # step 410"
        self._decisions: "collections.deque[dict]" = collections.deque(
            maxlen=max(1, int(decision_capacity)))
        # host-plane incidents (server/scheduler restarts, wire CRC
        # rejections — docs/resilience.md "Host-plane recovery"): a
        # bounded sibling ring fed by notify_host_incident, so a
        # forensics bundle shows recovery activity next to the step
        # records ("loss plateaued at step 812" reads differently next
        # to "the global server restarted at generation 3")
        self._incidents: "collections.deque[dict]" = collections.deque(
            maxlen=max(1, int(incident_capacity)))
        self.dumps: List[str] = []    # bundle paths written so far
        self.anomalies_seen = 0

    # ---- recording ---------------------------------------------------------

    def record(self, step: int, probes: Dict[str, Any], *,
               membership_version: int = 0,
               phases: Optional[Dict[str, float]] = None,
               extra: Optional[Dict[str, Any]] = None) -> List[dict]:
        """Append one per-step record and evaluate the anomaly rules
        against the ring's history.  Returns the fired anomalies (empty
        list when healthy); when ``dump_dir`` is set, any firing also
        writes the forensics bundle and appends its path to
        :attr:`dumps`."""
        rec: Dict[str, Any] = {
            "step": int(step),
            "membership_version": int(membership_version),
            "probes": dict(probes),
        }
        if phases is not None:
            rec["phases"] = dict(phases)
        if extra:
            rec["extra"] = dict(extra)
        fired = self._check(rec)
        self._ring.append(rec)
        if fired:
            self.anomalies_seen += len(fired)
            rec["anomalies"] = fired
            if self.dump_dir:
                self.dumps.append(self.dump(fired, rec))
        return fired

    def record_ledger(self, step: int, ledger=None,
                      now: Optional[float] = None,
                      **record_kw) -> List[dict]:
        """Feed one fleet-round-ledger summary (telemetry/ledger.py)
        through the ring as a probes record, so the ``stuck_round`` and
        ``honesty_ratio_drift`` rules evaluate against the rolling
        history exactly like every other rule.  ``ledger`` defaults to
        the process-global one; ``now`` pins the staleness clock for
        deterministic replays."""
        if ledger is None:
            from geomx_tpu.telemetry.ledger import get_round_ledger
            ledger = get_round_ledger()
        return self.record(step, ledger.summary(now=now), **record_kw)

    def snapshot(self) -> List[dict]:
        return list(self._ring)

    def record_decision(self, decision: Dict[str, Any]) -> None:
        """Append one controller actuation (a Decision's JSON form) to
        the bounded decision ring; it rides every subsequent forensics
        bundle."""
        self._decisions.append(dict(decision))

    def decisions(self) -> List[dict]:
        return list(self._decisions)

    def record_incident(self, kind: str,
                        detail: Optional[Dict[str, Any]] = None) -> None:
        """Append one host-plane incident (``server_restart`` /
        ``scheduler_restart`` / ``wire_crc_error``); it rides every
        subsequent forensics bundle.  Usually fed through the module's
        :func:`notify_host_incident` fan-out, not called directly."""
        self._incidents.append({"kind": str(kind),
                                "detail": dict(detail or {}),
                                "unix": round(time.time(), 6)})

    def incidents(self) -> List[dict]:
        return list(self._incidents)

    # ---- anomaly rules (pure functions of ring + new record) ---------------

    def _history(self, field: str, from_phases: bool = False
                 ) -> List[float]:
        out: List[float] = []
        for rec in list(self._ring)[-self.window:]:
            src = rec.get("phases") if from_phases else rec.get("probes")
            v = (src or {}).get(field)
            if v is not None:
                try:
                    out.append(float(v))
                except (TypeError, ValueError):
                    pass
        return _finite(out)

    def _check(self, rec: dict) -> List[dict]:
        fired: List[dict] = []
        probes = rec["probes"]

        # 1. nonfinite probe — fires immediately, names the party
        bad_scalars = []
        for name, v in probes.items():
            try:
                vals = v if isinstance(v, (list, tuple)) else [v]
                if any(not math.isfinite(float(u)) for u in vals):
                    bad_scalars.append(name)
            except (TypeError, ValueError):
                continue
        parties = probes.get("party_grad_nonfinite")
        poisoned = [i for i, flag in enumerate(parties or [])
                    if float(flag) > 0]
        if bad_scalars or poisoned or \
                float(probes.get("grad_all_finite", 1.0) or 0.0) < 1.0 \
                and "grad_all_finite" in probes:
            fired.append({"rule": NONFINITE, "step": rec["step"],
                          "nonfinite_probes": sorted(bad_scalars),
                          "poisoned_parties": poisoned})

        # 2. grad-norm spike vs rolling median
        hist = self._history("grad_norm_global")
        norm = probes.get("grad_norm_global")
        if norm is not None and len(hist) >= self.min_history:
            med = _median(hist)
            norm = float(norm)
            if math.isfinite(norm) and med > 0 \
                    and norm > self.spike_factor * med:
                fired.append({"rule": GRAD_SPIKE, "step": rec["step"],
                              "grad_norm": norm, "rolling_median": med,
                              "factor": norm / med})

        # 3. achieved-density drift (the in-situ compression ratio moved)
        hist = self._history("dc_nonzero_fraction")
        dens = probes.get("dc_nonzero_fraction")
        if dens is not None and len(hist) >= self.min_history:
            med = _median(hist)
            dens = float(dens)
            if math.isfinite(dens) and med > 0 and \
                    abs(dens - med) > self.density_drift * med:
                fired.append({"rule": DENSITY_DRIFT, "step": rec["step"],
                              "density": dens, "rolling_median": med,
                              "relative_drift": abs(dens - med) / med})

        # 4. exposed-comms fraction jump (the wire became the bottleneck)
        phases = rec.get("phases") or {}
        exp = phases.get("exposed_comms")
        hist = self._history("exposed_comms", from_phases=True)
        if exp is not None and len(hist) >= self.min_history:
            med = _median(hist)
            exp = float(exp)
            if math.isfinite(exp) and exp - med > self.exposed_jump:
                fired.append({"rule": EXPOSED_JUMP, "step": rec["step"],
                              "exposed_fraction": exp,
                              "rolling_median": med, "jump": exp - med})

        # 5. stuck round (fleet round ledger): an open round older than
        # the bound — a shard that died without failover, a sender that
        # will never satisfy the gate.  Immediate like the nonfinite
        # rule: the age itself already encodes the history.
        age = probes.get("ledger_open_round_age_s")
        if age is not None:
            try:
                age = float(age)
            except (TypeError, ValueError):
                age = None
        if age is not None and math.isfinite(age) \
                and age > self.stuck_round_s:
            fired.append({"rule": STUCK_ROUND, "step": rec["step"],
                          "open_round_age_s": age,
                          "open_rounds":
                              probes.get("ledger_open_rounds"),
                          "oldest_open":
                              probes.get("ledger_oldest_open")})

        # 6. honesty-ratio drift: measured-vs-declared wire bytes moved
        # relative to the rolling median — framing/retry overhead
        # creeping up, or a compressor's declared bytes going stale
        hist = self._history("wire_honesty_ratio")
        ratio = probes.get("wire_honesty_ratio")
        if ratio is not None and len(hist) >= self.min_history:
            med = _median(hist)
            ratio = float(ratio)
            if math.isfinite(ratio) and med > 0 and \
                    abs(ratio - med) > self.honesty_drift * med:
                fired.append({"rule": HONESTY_DRIFT, "step": rec["step"],
                              "honesty_ratio": ratio,
                              "rolling_median": med,
                              "relative_drift": abs(ratio - med) / med})
        return fired

    # ---- forensics bundle --------------------------------------------------

    def dump(self, fired: List[dict], rec: dict,
             path: Optional[str] = None) -> str:
        """Write the forensics bundle: the anomalies that fired, the
        triggering record, and the whole ring (oldest first).  Atomic
        (temp file + replace); the filename carries the step and first
        rule so concurrent anomalies never clobber each other."""
        if path is None:
            os.makedirs(self.dump_dir, exist_ok=True)
            rule = fired[0]["rule"] if fired else "manual"
            path = os.path.join(
                self.dump_dir, f"flight_step{rec['step']}_{rule}.json")
        poisoned = sorted({p for f in fired
                           for p in f.get("poisoned_parties", [])})
        # the counter/gauge state AT dump time: step records say what
        # the run published per step, but the registry holds the
        # cumulative truth (restart counters, CRC rejections, eviction
        # totals) a forensics read needs next to them.  Bounded by the
        # same size discipline as the ring: at most `capacity` children
        # per family, dropped children counted in the sample itself.
        try:
            from geomx_tpu.telemetry.capsule import sample_registry
            registry_section = sample_registry(
                max_children_per_family=self.capacity)
        except Exception:
            registry_section = {}
        bundle = {
            "kind": "geomx_flight_bundle",
            "written_unix": round(time.time(), 6),
            "step": rec["step"],
            "fired": fired,
            "poisoned_parties": poisoned,
            "trigger": rec,
            "ring": self.snapshot(),
            "decisions": self.decisions(),
            "incidents": self.incidents(),
            "registry": registry_section,
            "capacity": self.capacity,
        }
        from geomx_tpu.utils.atomicio import atomic_json_dump
        return atomic_json_dump(path, bundle)


# ---- host-plane incident fan-out ------------------------------------------
# The durable host plane (service/, docs/resilience.md) reports its
# recovery activity here: one call lands the incident in (a) the
# process-global registry counter, (b) the structured event log, and
# (c) every installed FlightRecorder's bounded incident ring, so
# forensics bundles show restarts and wire-CRC rejections next to the
# step records.  Recorders self-install via install_incident_recorder
# (the trainer does this when the flight recorder is armed).

_incident_lock = threading.Lock()
_incident_recorders: List["FlightRecorder"] = []


def install_incident_recorder(recorder: "FlightRecorder") -> None:
    with _incident_lock:
        if recorder not in _incident_recorders:
            _incident_recorders.append(recorder)


def uninstall_incident_recorder(recorder: "FlightRecorder") -> None:
    with _incident_lock:
        if recorder in _incident_recorders:
            _incident_recorders.remove(recorder)


def announce_host_restart(node: str, generation: int, kind: str,
                          **detail) -> None:
    """The one restart-announcement contract both host-plane singletons
    share: bump ``geomx_host_restarts_total{node}``, publish the
    ``geomx_host_generation{node}`` gauge, and fan the incident out
    (``kind`` is ``server_restart`` / ``scheduler_restart``)."""
    try:
        from geomx_tpu.telemetry import get_registry
        reg = get_registry()
        reg.counter("geomx_host_restarts_total",
                    "Host-plane process restarts recovered from the "
                    "durable store", ("node",)).labels(node=node).inc()
        reg.gauge("geomx_host_generation",
                  "Current durable generation per host-plane node",
                  ("node",)).labels(node=node).set(generation)
    except Exception:
        pass
    notify_host_incident(kind, generation=generation, **detail)


def notify_host_incident(kind: str, **detail) -> None:
    """Fan one host-plane incident out to the registry, the event log
    and every installed flight recorder.  Best-effort by design: the
    failure being reported must never be compounded by its reporting."""
    try:
        from geomx_tpu.telemetry import get_registry, log_event
        get_registry().counter(
            "geomx_host_incidents_total",
            "Host-plane incidents (restarts recovered from the durable "
            "store, wire integrity rejections)", ("kind",)).labels(
            kind=kind).inc()
        log_event(kind, **detail)
    except Exception:
        pass
    with _incident_lock:
        recorders = list(_incident_recorders)
    for rec in recorders:
        try:
            rec.record_incident(kind, detail)
        except Exception:
            pass


def flight_enabled(config: Optional[Any] = None) -> bool:
    """``GeoConfig(flight=True)`` or ``GEOMX_FLIGHT`` (same numeric-
    boolean parse as every GEOMX_* knob)."""
    if config is not None and getattr(config, "flight", False):
        return True
    from geomx_tpu.config import _env_bool
    return _env_bool(["GEOMX_FLIGHT"], False)


def flight_recorder_from_config(config: Optional[Any] = None
                                ) -> Optional[FlightRecorder]:
    """The trainer's constructor path: None when the recorder is off;
    otherwise a ring sized/parameterized from config + env
    (GEOMX_FLIGHT_STEPS and the rule-threshold rows)."""
    if not flight_enabled(config):
        return None
    from geomx_tpu.config import _env
    steps = getattr(config, "flight_steps", 0) or \
        _env(["GEOMX_FLIGHT_STEPS"], DEFAULT_STEPS,
             lambda s: int(float(s)))
    dump_dir = getattr(config, "flight_dir", "") or \
        _env(["GEOMX_FLIGHT_DIR"], "geomx_flight", str)
    return FlightRecorder(
        capacity=steps, dump_dir=dump_dir,
        spike_factor=_env(["GEOMX_FLIGHT_SPIKE"], 10.0, float),
        density_drift=_env(["GEOMX_FLIGHT_DENSITY_DRIFT"], 0.5, float),
        exposed_jump=_env(["GEOMX_FLIGHT_EXPOSED_JUMP"], 0.25, float),
        stuck_round_s=_env(["GEOMX_FLIGHT_STUCK_S"], 30.0, float),
        honesty_drift=_env(["GEOMX_FLIGHT_HONESTY_DRIFT"], 0.25, float))
