"""WAN link estimation: per-(party, peer) EWMA throughput/RTT/loss.

ROADMAP item 3's controller needs *measured* per-link quality before it
can retune compression ratio or re-form relay chains; PR 5's tracing
plane records the raw material (every ``RelayToGlobal:<key>`` span IS
one party's DCN round trip, with its payload bytes in the span args)
but nothing folds the spans into estimates.  :class:`LinkObservatory`
is that fold — and its :meth:`~LinkObservatory.snapshot` is the stable
sensor interface the controller will consume:

- :meth:`~LinkObservatory.observe` takes one transfer observation
  (bytes, seconds, ok) for a ``party -> peer`` link;
- :meth:`~LinkObservatory.ingest_trace` replays a Chrome trace dump (a
  single profiler dump or a ``merge_traces`` document): WAN relay spans
  become throughput/RTT observations, ``RelayFailure:*`` instants
  become loss observations;
- estimates are EWMAs (the reference TSEngine smooths its measured
  throughput the same way, ``transport/tsengine.py``), and every
  snapshot entry carries an ``age_s`` + exponentially-decayed
  ``confidence`` so a controller can tell a fresh estimate from one
  that predates the last membership change (staleness decay).

Timestamps are explicit (``t=``) or derived from the trace's wall-clock
anchor, never sampled inside the fold — replaying the same rounds twice
produces the same snapshot, which is what makes chaos-schedule replays
usable as the controller's acceptance harness.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, Optional, Tuple

_RELAY_PREFIXES = ("RelayToGlobal:", "RelayRowSparse:")
_FAILURE_PREFIX = "RelayFailure:"


class LinkEstimate:
    """EWMA state for one directed link."""

    __slots__ = ("throughput_bps", "rtt_s", "loss_rate", "samples",
                 "failures", "last_t", "bytes_total")

    def __init__(self):
        self.throughput_bps: Optional[float] = None
        self.rtt_s: Optional[float] = None
        self.loss_rate: float = 0.0
        self.samples: int = 0
        self.failures: int = 0
        self.bytes_total: float = 0.0
        self.last_t: Optional[float] = None

    def _ewma(self, old: Optional[float], new: float,
              alpha: float) -> float:
        return new if old is None else alpha * new + (1 - alpha) * old

    def update(self, *, nbytes: float, seconds: Optional[float],
               ok: bool, alpha: float, t: float) -> None:
        self.samples += 1
        self.last_t = t if self.last_t is None else max(self.last_t, t)
        if not ok:
            self.failures += 1
            self.loss_rate = self._ewma(self.loss_rate, 1.0, alpha)
            return
        self.loss_rate = self._ewma(self.loss_rate, 0.0, alpha)
        if seconds is not None and seconds > 0:
            self.rtt_s = self._ewma(self.rtt_s, seconds, alpha)
            if nbytes > 0:
                self.bytes_total += nbytes
                self.throughput_bps = self._ewma(
                    self.throughput_bps, nbytes / seconds, alpha)


def relay_order(records, peer: str = "global",
                min_confidence: float = 0.0) -> list:
    """Widest-uplink-first party order over snapshot records — THE
    relay ordering rule (throughput descending, unmeasured links last,
    ties broken by party name), shared by
    :meth:`LinkObservatory.best_relay_order` and the control plane's
    ``RelayPolicy`` so the published order and the policy's chain can
    never drift.  ``records``: snapshot-record dicts (``party`` /
    ``peer`` / ``throughput_bps`` / ``confidence``)."""
    entries = [r for r in records if r["peer"] == peer
               and r["confidence"] >= min_confidence]
    entries.sort(key=lambda r: (
        -(r["throughput_bps"]
          if r["throughput_bps"] is not None else -math.inf),
        r["party"]))
    return [r["party"] for r in entries]


class LinkObservatory:
    """Fold WAN round observations into per-link quality estimates.

    ``alpha``: EWMA smoothing factor (weight of the newest sample).
    ``stale_after_s``: confidence half-life — a snapshot taken
    ``stale_after_s`` after the last observation reports confidence
    0.5, two half-lives 0.25, ...; ``stale`` flips at < 0.5.
    """

    def __init__(self, alpha: float = 0.3, stale_after_s: float = 30.0):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1] (got {alpha!r})")
        if stale_after_s <= 0:
            raise ValueError(
                f"stale_after_s must be > 0 (got {stale_after_s!r})")
        self.alpha = float(alpha)
        self.stale_after_s = float(stale_after_s)
        self._lock = threading.Lock()
        self._links: Dict[Tuple[str, str], LinkEstimate] = {}
        self._tap = None

    def set_tap(self, fn) -> None:
        """Install (or clear, with None) an observation tap: ``fn``
        receives every :meth:`observe` call as one plain dict
        ``{party, peer, nbytes, seconds, ok, t}`` with the RESOLVED
        timestamp.  The run-capsule recorder
        (:mod:`geomx_tpu.telemetry.capsule`) uses this as its link
        journal; replaying the journal through a fresh observatory in
        order reproduces the EWMA state bit-identically.  The tap is
        called under the observatory lock so journal order always
        equals fold order — it must be cheap and non-blocking (a list
        append)."""
        with self._lock:
            self._tap = fn

    # ---- write side --------------------------------------------------------

    def observe(self, party: str, peer: str = "global", *,
                nbytes: float = 0.0, seconds: Optional[float] = None,
                ok: bool = True, t: Optional[float] = None) -> None:
        """One transfer observation on the ``party -> peer`` link:
        ``nbytes`` moved in ``seconds`` (the span duration — RTT plus
        transfer, which is what the relay actually waits), ``ok=False``
        for a failed round (loss).  ``t`` is the observation's wall
        clock; pass it when replaying recorded rounds so the staleness
        clock is the replay's, not the fold's."""
        t = time.time() if t is None else float(t)
        key = (str(party), str(peer))
        with self._lock:
            if self._tap is not None:
                self._tap({
                    "party": key[0], "peer": key[1],
                    "nbytes": float(nbytes),
                    "seconds": None if seconds is None else float(seconds),
                    "ok": bool(ok), "t": t})
            est = self._links.get(key)
            if est is None:
                est = self._links[key] = LinkEstimate()
            est.update(nbytes=float(nbytes), seconds=seconds, ok=bool(ok),
                       alpha=self.alpha, t=t)

    def ingest_trace(self, doc: dict,
                     party: Optional[str] = None,
                     peer: str = "global") -> int:
        """Replay a Chrome trace document's WAN rounds into the
        estimators; returns the number of observations folded.

        Works on a single profiler dump (party from ``metadata.rank`` or
        the ``party`` argument) and on a ``merge_traces`` document
        (party from each pid's ``process_name`` row).  Spans named
        ``RelayToGlobal:*`` / ``RelayRowSparse:*`` contribute
        throughput+RTT (payload bytes from the span args); instants
        named ``RelayFailure:*`` contribute loss."""
        from geomx_tpu.telemetry.tracing import process_names
        names = process_names(doc)
        meta = doc.get("metadata") or {}
        anchor_us = meta.get("anchor_unix_us")
        rank = meta.get("rank")
        default_party = party if party is not None else (
            f"rank{rank}" if rank is not None else "party0")

        folded = 0
        for ev in doc.get("traceEvents", []):
            name = ev.get("name", "")
            who = names.get(ev.get("pid"), default_party) \
                if names else default_party
            t = None
            if anchor_us is not None and "ts" in ev:
                t = (float(anchor_us) + float(ev["ts"])) / 1e6
            if ev.get("ph") == "X" and name.startswith(_RELAY_PREFIXES):
                args = ev.get("args") or {}
                self.observe(
                    who, peer,
                    nbytes=float(args.get("payload_bytes")
                                 or args.get("bytes") or 0.0),
                    seconds=float(ev.get("dur", 0.0)) / 1e6,
                    ok=True, t=t)
                folded += 1
            elif ev.get("ph") == "i" and name.startswith(_FAILURE_PREFIX):
                self.observe(who, peer, ok=False, t=t)
                folded += 1
        return folded

    def ingest_ledger(self, records, peer: str = "global") -> int:
        """Fold fleet-round-ledger records (``RoundLedger.records()``
        dicts, telemetry/ledger.py) into the link estimators — the
        on-wire-truth sensor path: unlike trace spans, ledger bytes
        are measured at the wire choke point, so the Pilot's
        throughput estimates see framing/retry overhead too.

        Per record: every ``relay`` hop is one throughput+RTT
        observation on its party's uplink; records WITHOUT a relay hop
        (a flat worker->shard fleet) contribute one observation per
        pushing party — that party's measured push bytes over the
        push->merge interval, which is what the round actually waited.
        Orphaned records count as one loss observation.  Timestamps
        come from the hops, never the fold — same records, same
        snapshot."""
        folded = 0
        for rec in records:
            hops = rec.get("hops") or []
            relays = [h for h in hops if h["hop"] == "relay"]
            orphaned = rec.get("status") == "orphaned"
            for h in relays:
                p = h.get("party")
                if p is None:
                    p = rec.get("origin_party") or 0
                self.observe(f"party{p}", peer,
                             nbytes=float(h.get("nbytes") or 0.0),
                             seconds=h.get("dur_s"), ok=not orphaned,
                             t=h.get("t"))
                folded += 1
            if relays:
                continue
            merge = next((h for h in hops if h["hop"] == "merge"), None)
            pushes: Dict[int, list] = {}
            for h in hops:
                if h["hop"] == "push" and h.get("party") is not None:
                    pushes.setdefault(int(h["party"]), []).append(h)
            for party, phops in sorted(pushes.items()):
                nbytes = float(sum(h.get("nbytes") or 0 for h in phops))
                t0 = min(h["t"] for h in phops)
                seconds = None
                if merge is not None and merge["t"] > t0:
                    seconds = merge["t"] - t0
                self.observe(f"party{party}", peer, nbytes=nbytes,
                             seconds=seconds, ok=not orphaned,
                             t=merge["t"] if merge is not None else t0)
                folded += 1
            if not pushes and orphaned:
                self.observe(f"party{rec.get('origin_party') or 0}",
                             peer, ok=False,
                             t=rec.get("closed_unix"))
                folded += 1
        return folded

    # ---- read side (the controller's sensor interface) ---------------------

    def snapshot(self, now: Optional[float] = None,
                 min_confidence: Optional[float] = None) -> Dict[str, dict]:
        """The current estimate per link, keyed ``"<party>-><peer>"``:
        ``throughput_bps`` / ``rtt_s`` / ``loss_rate`` EWMAs, sample and
        failure counts, and the staleness pair (``age_s``,
        ``confidence`` = 2^(-age/half-life), ``stale`` below 0.5).
        Deterministic for a given ``now``.

        ``min_confidence`` filters out links whose staleness-decayed
        confidence has fallen below the threshold — the one staleness
        gate every policy consumer shares instead of re-implementing
        (docs/control.md)."""
        now = time.time() if now is None else float(now)
        out: Dict[str, dict] = {}
        with self._lock:
            for (party, peer), est in sorted(self._links.items()):
                age = max(now - est.last_t, 0.0) \
                    if est.last_t is not None else math.inf
                conf = 2.0 ** (-age / self.stale_after_s) \
                    if math.isfinite(age) else 0.0
                if min_confidence is not None and conf < min_confidence:
                    continue
                out[f"{party}->{peer}"] = {
                    "party": party, "peer": peer,
                    "throughput_bps": est.throughput_bps,
                    "rtt_s": est.rtt_s,
                    "loss_rate": est.loss_rate,
                    "samples": est.samples,
                    "failures": est.failures,
                    "bytes_total": est.bytes_total,
                    "age_s": age,
                    "confidence": conf,
                    "stale": conf < 0.5,
                }
        return out

    def best_relay_order(self, peer: str = "global",
                         now: Optional[float] = None,
                         min_confidence: float = 0.0) -> list:
        """Parties ordered widest-uplink-first toward ``peer`` — the
        greedy widest-path relay chain the paper's TSEngine forms
        (ProcessAsk1Command pairs the lower-throughput node to send
        through the higher-throughput one; the widest link sits next to
        the sink).  Deterministic: throughput descending, unmeasured
        links last, ties broken by party name (:func:`relay_order` —
        the one ordering rule the control plane's RelayPolicy shares).
        Links below ``min_confidence`` are excluded up front (same
        staleness gate as :meth:`snapshot`)."""
        snap = self.snapshot(now=now, min_confidence=min_confidence or None)
        return relay_order(snap.values(), peer=peer)

    def publish(self, registry=None, now: Optional[float] = None) -> None:
        """Export the snapshot as registry gauges
        (``geomx_link_*{party,peer}``) for the scheduler's ``/metrics``
        surface."""
        from geomx_tpu.telemetry.registry import get_registry
        reg = registry if registry is not None else get_registry()
        labels = ("party", "peer")
        fams = {
            "throughput_bps": reg.gauge(
                "geomx_link_throughput_bps",
                "EWMA WAN link throughput", labels),
            "rtt_s": reg.gauge(
                "geomx_link_rtt_seconds",
                "EWMA WAN relay round-trip time", labels),
            "loss_rate": reg.gauge(
                "geomx_link_loss_rate",
                "EWMA WAN relay failure rate", labels),
            "confidence": reg.gauge(
                "geomx_link_confidence",
                "Staleness-decayed estimate confidence", labels),
        }
        for rec in self.snapshot(now=now).values():
            for field, fam in fams.items():
                val = rec[field]
                if val is not None:
                    fam.labels(party=rec["party"],
                               peer=rec["peer"]).set(float(val))


# process-global observatory: the host plane (GeoPSServer relays) and
# the controller read/write one instance per process
_global: Optional[LinkObservatory] = None
_global_lock = threading.Lock()


def get_link_observatory() -> LinkObservatory:
    global _global
    with _global_lock:
        if _global is None:
            _global = LinkObservatory()
        return _global


def reset_link_observatory() -> LinkObservatory:
    """Fresh global observatory (test isolation)."""
    global _global
    with _global_lock:
        _global = LinkObservatory()
        return _global
