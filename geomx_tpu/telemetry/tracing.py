"""Cross-party WAN round tracing: merge N Chrome traces into one timeline.

The host plane already records per-process Chrome traces
(``utils/profiler.py``): a local server's ``RelayToGlobal:<key>`` span
is its WAN push+pull, the global server's ``ServerPush:<key>`` /
``ServerMerge:<key>`` / ``ServerPull:<key>`` events are the far side.
What was missing is *correlation*: which party's relay belongs to which
global round, and one timeline to see the straggler on.

Two pieces close that gap:

- a ``round_id`` rides the span ``args`` end to end — the client's
  per-key push round counter (``GeoPSClient._key_rounds``) is the wire
  round id, the server threads it through merge completion, the WAN
  relay queue and the pull replies (``service/server.py``);
- :func:`merge_traces` folds N parties' trace dumps into one document:
  every input becomes a named Chrome process, timestamps are aligned on
  each dump's wall-clock anchor (``metadata.anchor_unix_us``, written
  by ``Profiler.dump``) so skewed per-process monotonic clocks land on
  one real timeline, and every ``(key, round_id)`` group is stitched
  with Chrome *flow events* — load the merged file in
  ``chrome://tracing``/Perfetto and each WAN round draws as one arrow
  chain across parties.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

ROUND_FLOW_CAT = "wan_round"


def _load(trace) -> dict:
    if isinstance(trace, str):
        with open(trace) as f:
            return json.load(f)
    return dict(trace)


def round_key(event: dict) -> Optional[Tuple[str, int]]:
    """The (key, round_id) a trace event is correlated under, or None."""
    args = event.get("args") or {}
    rid = args.get("round_id")
    if rid is None:
        return None
    key = args.get("key")
    if key is None:
        # spans name themselves "<What>:<key>"
        name = event.get("name", "")
        key = name.split(":", 1)[1] if ":" in name else name
    return (str(key), int(rid))


def merge_traces(traces: Sequence[Any],
                 labels: Optional[Sequence[str]] = None) -> dict:
    """Merge Chrome trace docs (paths or dicts) into one document.

    Each input becomes its own Chrome process (pid = input index) with a
    ``process_name`` metadata row; event timestamps shift onto a shared
    wall-clock axis using each dump's ``metadata.anchor_unix_us`` (inputs
    without an anchor keep their own zero — correct only for same-clock
    dumps, flagged in the output metadata).  Spans/instants whose args
    carry a ``round_id`` are linked per ``(key, round_id)`` with flow
    events ordered by merged timestamp.
    """
    docs = [_load(t) for t in traces]
    anchors = [
        (d.get("metadata") or {}).get("anchor_unix_us") for d in docs]
    known = [a for a in anchors if a is not None]
    base = min(known) if known else 0.0

    out_events: List[dict] = []
    rounds: Dict[Tuple[str, int], List[dict]] = {}
    for i, doc in enumerate(docs):
        shift = (anchors[i] - base) if anchors[i] is not None else 0.0
        if labels is not None and i < len(labels):
            label = labels[i]
        else:
            rank = (doc.get("metadata") or {}).get("rank")
            label = f"rank{rank}" if rank is not None else f"party{i}"
        out_events.append({"name": "process_name", "ph": "M", "pid": i,
                           "tid": 0, "args": {"name": label}})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = i
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift
            out_events.append(ev)
            rk = round_key(ev)
            if rk is not None and ev.get("ph") in ("X", "i"):
                rounds.setdefault(rk, []).append(ev)

    # one flow chain per WAN round: s -> t... -> f in timestamp order.
    # Binding point is each event's own (pid, tid, ts), which Chrome
    # attaches to the enclosing slice.
    flow_id = 0
    for (key, rid), evs in sorted(rounds.items()):
        if len(evs) < 2:
            continue
        flow_id += 1
        evs = sorted(evs, key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
        for j, ev in enumerate(evs):
            ph = "s" if j == 0 else ("f" if j == len(evs) - 1 else "t")
            flow = {"name": f"round {rid}", "cat": ROUND_FLOW_CAT,
                    "ph": ph, "id": flow_id,
                    "ts": ev.get("ts", 0.0),
                    "pid": ev.get("pid", 0), "tid": ev.get("tid", 0),
                    "args": {"key": key, "round_id": rid}}
            if ph == "f":
                flow["bp"] = "e"  # bind to enclosing slice
            out_events.append(flow)

    return {
        "traceEvents": out_events,
        "displayTimeUnit": "ms",
        "metadata": {
            "merged_from": len(docs),
            "clock_aligned": all(a is not None for a in anchors),
            "anchor_unix_us": base,
            "wan_rounds": len(rounds),
        },
    }


def process_names(doc: dict) -> Dict[int, str]:
    """pid -> label from a trace's ``process_name`` metadata rows (what
    :func:`merge_traces` writes per party) — the one place the metadata
    shape is known to the observatory consumers (attribution, links)."""
    names: Dict[int, str] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            names[ev.get("pid", 0)] = (ev.get("args") or {}).get(
                "name", str(ev.get("pid")))
    return names


def rounds_in_trace(doc: dict) -> Dict[Tuple[str, int], List[dict]]:
    """Group a (merged or single) trace's correlated events by
    (key, round_id) — the assertion surface for tests and bench."""
    out: Dict[Tuple[str, int], List[dict]] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") not in ("X", "i"):
            continue
        rk = round_key(ev)
        if rk is not None:
            out.setdefault(rk, []).append(ev)
    return out
