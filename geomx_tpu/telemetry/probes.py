"""In-graph step-health probes.

"Evaluation and Optimization of Gradient Compression for Distributed
Deep Learning" (PAPERS.md) makes the case that achieved compression and
error-feedback magnitude must be measured *in situ* — a bench-time
estimate says nothing about the ratio a production run is actually
getting, or about the step where a party's gradient went NaN.  These
probes compute that evidence as cheap scalars **inside the jitted
step**, riding the existing metrics output: no extra dispatch, no host
round trip beyond the device_get the training loop already does.

The master switch is ``GEOMX_TELEMETRY`` (or ``GeoConfig(telemetry=
True)``).  The gate is *static at trace time* and guards a single call
site in ``train/step.py``: with telemetry off, the traced step's jaxpr
is byte-identical to a build with this module excised (pinned by
``tests/test_telemetry.py`` and re-verified by ``bench.py
--compare-telemetry``), so the default-off path costs exactly nothing.

Probe catalog (all values replicated across the mesh, so they ride the
replicated metrics output):

- ``grad_norm_global``       L2 norm of the applied (post-sync) gradient
- ``grad_all_finite``        1.0 iff the applied gradient has no NaN/Inf
- ``grad_nonfinite_count``   number of non-finite applied-grad elements
- ``party_grad_nonfinite``   per-party 0/1 vector: party's RAW gradient
                             (pre-dc-aggregation) contains NaN/Inf —
                             the "which party is poisoning the mean"
                             signal the aggregated value hides
- ``dc_nonzero_fraction``    achieved density of the dc aggregate (the
                             in-situ sparsity a top-k compressor really
                             delivered, post-aggregation)
- ``ef_residual_norm``       party-mean L2 norm of the dc-tier error-
                             feedback state (sync.telemetry_scalars)
- ``bsc_emitted_fraction``   fraction of the fixed-k wire slots carrying
                             real (non-sentinel) pairs, recorded inline
                             by the BSC compressor per bucket
- ``pipeline_*``             staleness / in-flight accounting when the
                             pipelined engine is active
- ``dc_wire_bytes`` / ``dc_dense_bytes`` / ``dc_compression_ratio`` /
  ``worker_wire_bytes``      static per-step wire accounting
  (``sync.wire_accounting``), folded in as constants so the host plane
  reads one dict
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def canonicalize_jaxpr(text: str) -> str:
    """Strip run-dependent noise from a jaxpr's string form so two
    traces of the SAME program compare equal: the only non-deterministic
    tokens are function object addresses in custom_jvp thunk params
    (``<function ... at 0x...>``).  The jaxpr-identity verdict (bench
    --compare-telemetry, tests/test_telemetry.py) compares on this."""
    import re
    return re.sub(r" at 0x[0-9a-fA-F]+>", " at 0xADDR>", text)


def telemetry_enabled(config: Optional[Any] = None) -> bool:
    """The master telemetry gate: ``config.telemetry`` or
    ``GEOMX_TELEMETRY``, parsed with the same numeric-boolean rules as
    every other GEOMX_* knob (``GeoConfig``'s ``_env_bool`` — so
    ``GEOMX_TELEMETRY=false`` raises loudly in BOTH readers instead of
    silently enabling here while the config rejects it).  Static —
    evaluated when the step program is *built*, so flipping it is a
    rebuild, never a silent recompile."""
    if config is not None and getattr(config, "telemetry", False):
        return True
    from geomx_tpu.config import _env_bool
    return _env_bool(["GEOMX_TELEMETRY"], False)


# ---------------------------------------------------------------------------
# inline recording: compressors deep inside the sync stack contribute
# probe scalars without threading a sink through every signature
# ---------------------------------------------------------------------------

_inline = threading.local()


@contextlib.contextmanager
def inline_collection():
    """Open a trace-time sink for :func:`record_inline`.  The traced
    step wraps its sync calls in this context only when telemetry is
    enabled, so the disabled path never even evaluates the probe
    expressions (``record_inline`` takes a thunk for exactly that
    reason)."""
    prev = getattr(_inline, "sink", None)
    sink: List[Tuple[str, jax.Array]] = []
    _inline.sink = sink
    try:
        yield sink
    finally:
        _inline.sink = prev


def inline_active() -> bool:
    return getattr(_inline, "sink", None) is not None


def record_inline(name: str, value_fn) -> None:
    """Record ``value_fn()`` (a traced scalar) under ``name`` into the
    active collection; no-op — without calling the thunk, so zero ops
    enter the jaxpr — when no collection is open."""
    sink = getattr(_inline, "sink", None)
    if sink is not None:
        sink.append((name, value_fn()))


# ---------------------------------------------------------------------------
# probe computation
# ---------------------------------------------------------------------------

def _float_leaves(tree) -> List[jax.Array]:
    return [leaf for leaf in jax.tree.leaves(tree)
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating)]


def _tree_sumsq(tree) -> jax.Array:
    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves)


def tree_norm(tree) -> jax.Array:
    """L2 norm over every floating leaf of ``tree`` (0.0 when none)."""
    return jnp.sqrt(_tree_sumsq(tree))


def _nonfinite_count(tree) -> jax.Array:
    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum((~jnp.isfinite(leaf)).astype(jnp.float32))
               for leaf in leaves)


def _replicate(x: jax.Array, sync: Any) -> jax.Array:
    """Party-local scalar -> mesh-replicated mean over LIVE parties
    (metrics out-spec is fully replicated).  Under a degraded membership
    mask the dead parties' devices still run the step (masked to zeros,
    residuals reset), so a plain dc pmean would dilute every probe by
    dead/total — the same survivor-weighted algebra step.py applies to
    loss/accuracy applies here."""
    from geomx_tpu.topology import DC_AXIS, WORKER_AXIS
    if getattr(sync, "workers_per_party", 1) > 1:
        x = lax.pmean(x, WORKER_AXIS)
    if getattr(sync, "num_parties", 1) > 1:
        w = sync.party_weight()
        if w is None:
            x = lax.pmean(x, DC_AXIS)
        else:
            x = lax.psum(x * w, DC_AXIS) / sync.num_live
    return x


def collect_step_probes(raw_grads: Any, synced_grads: Optional[Any],
                        sync: Any, sync_state: Any,
                        inline: Optional[List[Tuple[str, jax.Array]]],
                        params: Any) -> Dict[str, jax.Array]:
    """Assemble the probe dict inside the traced step.

    ``raw_grads``: this device's gradients before any cross-party
    aggregation (post sequence-parallel reduction); ``synced_grads``:
    the applied (dc-aggregated, replicated) gradient, or None on paths
    that fuse sync+update (MultiGPS); ``inline``: scalars recorded by
    compressors during the sync calls.  Every returned value is
    replicated across the mesh.
    """
    from geomx_tpu.topology import DC_AXIS, WORKER_AXIS
    nw = getattr(sync, "workers_per_party", 1)
    out: Dict[str, jax.Array] = {}

    # per-party NaN/Inf flag from the RAW gradients: aggregation (and a
    # mean over healthy parties) can mask one party's poison — the
    # per-party vector points at the culprit
    local_bad = _nonfinite_count(raw_grads)
    party_bad = lax.psum(local_bad, WORKER_AXIS) if nw > 1 else local_bad
    party_flag = (party_bad > 0).astype(jnp.float32)
    out["party_grad_nonfinite"] = lax.all_gather(party_flag, DC_AXIS)
    out["grad_nonfinite_parties"] = jnp.sum(out["party_grad_nonfinite"])

    if synced_grads is not None:
        # the applied gradient is replicated — no collective needed
        out["grad_norm_global"] = tree_norm(synced_grads)
        bad = _nonfinite_count(synced_grads)
        out["grad_nonfinite_count"] = bad
        out["grad_all_finite"] = (bad == 0).astype(jnp.float32)
        leaves = _float_leaves(synced_grads)
        total = sum(leaf.size for leaf in leaves) or 1
        nz = sum(jnp.sum((leaf != 0).astype(jnp.float32)) for leaf in leaves) \
            if leaves else jnp.zeros((), jnp.float32)
        out["dc_nonzero_fraction"] = nz / total

    # sync-algorithm scalars (EF residual norms, pipeline buffers):
    # party-local state, folded to the live-party mean
    for name, val in (sync.telemetry_scalars(sync_state) or {}).items():
        out[name] = _replicate(jnp.asarray(val, jnp.float32), sync)

    # inline recordings (e.g. BSC's per-bucket emitted fraction): mean
    # over recordings, then over the mesh
    if inline:
        grouped: Dict[str, List[jax.Array]] = {}
        for name, val in inline:
            grouped.setdefault(name, []).append(
                jnp.asarray(val, jnp.float32))
        for name, vals in grouped.items():
            mean = sum(vals) / len(vals)
            out[name] = _replicate(mean, sync)

    # static wire accounting as constants: the host plane reads probe
    # values and wire volume from the same dict
    for name, val in (sync.wire_accounting(params) or {}).items():
        out[name] = jnp.asarray(float(val), jnp.float32)
    return out
