"""Telemetry read side: Prometheus text exposition + bounded JSONL events.

Two export surfaces over the process-global registry
(:mod:`geomx_tpu.telemetry.registry`):

- :func:`render_prometheus` emits the Prometheus text exposition format
  (version 0.0.4), served live from the scheduler's HTTP endpoint
  (``GeoScheduler(metrics_port=...)`` -> ``GET /metrics``) and over the
  framework wire protocol as ``COMMAND {cmd: "metrics"}`` on both
  ``GeoPSServer`` and ``GeoScheduler`` — so a worker behind the PS
  protocol and an operator with curl read the same series;
- :class:`EventLog` appends structured JSON lines (one event per line)
  to a size-bounded file with single-generation rotation — the
  machine-readable trail of step probes, membership transitions and
  relay failures that outlives the process.

:func:`parse_prometheus_text` is the minimal parser the test suite (and
``bench.py --compare-telemetry``) round-trips the exposition through —
it understands exactly what :func:`render_prometheus` can produce, which
is the point: a rendering the parser rejects is a bug in the renderer.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from geomx_tpu.telemetry.registry import (HistogramChild, MetricRegistry,
                                          get_registry)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_str(names, values, extra: Tuple[str, str] = None) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape_label(extra[1])}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_prometheus(registry: Optional[MetricRegistry] = None) -> str:
    """The registry as Prometheus text exposition (format 0.0.4)."""
    registry = registry if registry is not None else get_registry()
    out: List[str] = []
    for fam in registry.collect():
        out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        out.append(f"# TYPE {fam.name} {fam.type}")
        for values, child in fam.children():
            if isinstance(child, HistogramChild):
                cum, total, count = child.snapshot()
                bounds = [_fmt_value(b) for b in child.upper_bounds]
                bounds.append("+Inf")
                for ub, c in zip(bounds, cum):
                    out.append(
                        f"{fam.name}_bucket"
                        f"{_labels_str(fam.label_names, values, ('le', ub))}"
                        f" {c}")
                ls = _labels_str(fam.label_names, values)
                out.append(f"{fam.name}_sum{ls} {_fmt_value(total)}")
                out.append(f"{fam.name}_count{ls} {count}")
            else:
                out.append(f"{fam.name}"
                           f"{_labels_str(fam.label_names, values)} "
                           f"{_fmt_value(child.value)}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# the minimal parser the exposition round-trips through
# ---------------------------------------------------------------------------

def _parse_value(s: str) -> float:
    if s == "+Inf":
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    if s == "NaN":
        return float("nan")
    return float(s)


def _parse_labels(s: str) -> Dict[str, str]:
    """Parse '{a="x",b="y"}' honoring \\" escapes."""
    labels: Dict[str, str] = {}
    i = 0
    s = s.strip()
    if not s:
        return labels
    if s[0] != "{" or s[-1] != "}":
        raise ValueError(f"malformed label set {s!r}")
    s = s[1:-1]
    while i < len(s):
        eq = s.index("=", i)
        name = s[i:eq].strip().lstrip(",").strip()
        if s[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {s[eq:]!r}")
        j = eq + 2
        buf = []
        while True:
            c = s[j]
            if c == "\\":
                nxt = s[j + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
                continue
            if c == '"':
                break
            buf.append(c)
            j += 1
        labels[name] = "".join(buf)
        i = j + 1
    return labels


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse exposition text into
    ``{family: {"type", "help", "samples": [(name, labels, value)]}}``.

    Strict about what the renderer is allowed to emit: every sample must
    belong to a family announced by a preceding ``# TYPE`` line
    (histogram samples match via the _bucket/_sum/_count suffixes), and
    histogram series must carry ``le`` labels with non-decreasing
    cumulative counts ending in ``+Inf``.
    """
    families: Dict[str, Dict[str, Any]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            families.setdefault(name, {"type": None, "help": "",
                                       "samples": []})["help"] = help_
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, type_ = rest.partition(" ")
            type_ = type_.strip()
            if type_ not in ("counter", "gauge", "histogram"):
                raise ValueError(f"unknown TYPE {type_!r} for {name}")
            families.setdefault(name, {"type": None, "help": "",
                                       "samples": []})["type"] = type_
            continue
        if line.startswith("#"):
            continue
        # sample: name{labels} value
        brace = line.find("{")
        if brace >= 0:
            close = line.rindex("}")
            sname = line[:brace]
            labels = _parse_labels(line[brace:close + 1])
            value = _parse_value(line[close + 1:].strip().split()[0])
        else:
            sname, _, rest = line.partition(" ")
            labels = {}
            value = _parse_value(rest.strip().split()[0])
        fam = None
        for cand in (sname, sname.rsplit("_bucket", 1)[0],
                     sname.rsplit("_sum", 1)[0],
                     sname.rsplit("_count", 1)[0]):
            if cand in families:
                fam = cand
                break
        if fam is None:
            raise ValueError(f"sample {sname!r} has no TYPE line")
        families[fam]["samples"].append((sname, labels, value))
    # histogram invariants
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        series: Dict[tuple, List[Tuple[float, float]]] = {}
        for sname, labels, value in fam["samples"]:
            if sname != f"{name}_bucket":
                continue
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            series.setdefault(key, []).append(
                (_parse_value(labels["le"]), value))
        for key, pts in series.items():
            pts.sort(key=lambda p: p[0])
            if not pts or pts[-1][0] != float("inf"):
                raise ValueError(f"{name}: bucket series {key} lacks +Inf")
            counts = [c for _le, c in pts]
            if any(b < a for a, b in zip(counts, counts[1:])):
                raise ValueError(f"{name}: non-cumulative buckets {key}")
    return families


# ---------------------------------------------------------------------------
# shared HTTP export surface (scheduler AND GeoPSServer serve the same
# routes — PR 5 gave only the scheduler an HTTP port, so fleet scrapers
# had to speak the wire protocol to reach a shard's registry)
# ---------------------------------------------------------------------------

def ledger_document(summary_only: bool = False,
                    max_records: int = 0) -> Dict[str, Any]:
    """The ``GET /ledger`` body: round-ledger records + summary, plus
    the serving plane's request section when a request ledger exists.
    ``summary=1`` drops the record arrays entirely and ``n=K`` bounds
    them to the most recent K — FleetScope polls every interval, and
    shipping the full ring each tick is O(GEOMX_LEDGER_ROUNDS) of JSON
    per node per poll."""
    from geomx_tpu.telemetry.ledger import (get_round_ledger,
                                            peek_request_ledger)

    def _section(led) -> Dict[str, Any]:
        sec: Dict[str, Any] = {"summary": led.summary()}
        if not summary_only:
            recs = led.records()
            if max_records > 0:
                recs = recs[-max_records:]
            sec["records"] = recs
        return sec

    doc = _section(get_round_ledger())
    req_led = peek_request_ledger()
    if req_led is not None:
        doc["requests"] = _section(req_led)
    return doc


def start_http_exporter(bind_host: str, port: int, health_fn=None,
                        routes: Optional[Dict[str, Any]] = None,
                        post_routes: Optional[Dict[str, Any]] = None,
                        thread_name: str = "metrics-http"):
    """Serve the standard observability routes from a daemon HTTP
    thread: ``GET /metrics`` (Prometheus text exposition of the
    process-global registry), ``GET /healthz`` (``health_fn()`` as
    JSON), and ``GET /ledger`` (the process-global fleet round
    ledger's records + summary plus the serving plane's per-request
    ledger when one exists, telemetry/ledger.py; ``?summary=1`` drops
    the record arrays, ``?n=K`` bounds them — the FleetScope poll
    shapes).  ``routes`` maps
    extra GET paths to zero-arg callables returning ``(body_bytes,
    content_type)`` (the scheduler adds ``/control``); ``post_routes``
    maps POST paths to one-arg callables ``body_bytes -> (status,
    body_bytes, content_type)`` (the serving gateway adds ``/infer``).
    Returns the ``ThreadingHTTPServer`` (``.server_address[1]`` is the
    bound port; callers own ``shutdown()``/``server_close()``)."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    extra = dict(routes or {})
    extra_post = dict(post_routes or {})

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(h):  # noqa: N805 — http.server handler convention
            route, _, query = h.path.partition("?")
            route = route.rstrip("/")
            try:
                if route in ("", "/metrics"):
                    body = render_prometheus().encode("utf-8")
                    ctype = CONTENT_TYPE
                elif route == "/healthz" and health_fn is not None:
                    body = _json.dumps(
                        health_fn(), default=_json_default).encode("utf-8")
                    ctype = "application/json"
                elif route == "/ledger":
                    from urllib.parse import parse_qs
                    params = parse_qs(query)
                    summary_only = params.get(
                        "summary", ["0"])[-1] in ("1", "true", "yes")
                    try:
                        max_records = int(params.get("n", ["0"])[-1])
                    except ValueError:
                        max_records = 0
                    doc = ledger_document(summary_only=summary_only,
                                          max_records=max_records)
                    body = _json.dumps(
                        doc, default=_json_default).encode("utf-8")
                    ctype = "application/json"
                elif route in extra:
                    body, ctype = extra[route]()
                else:
                    h.send_response(404)
                    h.end_headers()
                    return
            except Exception:
                h.send_response(500)
                h.end_headers()
                return
            h.send_response(200)
            h.send_header("Content-Type", ctype)
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)

        def do_POST(h):  # noqa: N805 — http.server handler convention
            route = h.path.partition("?")[0].rstrip("/")
            fn = extra_post.get(route)
            if fn is None:
                h.send_response(404)
                h.end_headers()
                return
            try:
                n = int(h.headers.get("Content-Length") or 0)
                payload = h.rfile.read(n) if n > 0 else b""
                status, body, ctype = fn(payload)
            except Exception:
                h.send_response(500)
                h.end_headers()
                return
            h.send_response(int(status))
            h.send_header("Content-Type", ctype)
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)

        def log_message(self, *args):  # no per-scrape stderr noise
            pass

    srv = ThreadingHTTPServer((bind_host, port), _Handler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, name=thread_name,
                     daemon=True).start()
    return srv


# ---------------------------------------------------------------------------
# bounded JSONL structured event log
# ---------------------------------------------------------------------------

class EventLog:
    """Append-only JSON-lines event log with a byte cap.

    Each event is one line ``{"ts": <unix seconds>, "kind": ..., ...}``.
    When the file would exceed ``max_bytes`` the current file rotates to
    ``<path>.1`` (one generation — the log is bounded at ~2x max_bytes
    on disk, never unbounded) and a fresh file starts with a ``rotated``
    marker event.  Writes are line-atomic under an internal lock; the
    rotation itself uses ``os.replace`` so a crash never leaves a
    half-moved file.

    Emitting is BEST-EFFORT: an IO failure (full disk, revoked
    directory) drops the event and bumps ``write_errors`` instead of
    raising — telemetry must never take down the subsystem it observes
    (a membership publish aborted by its own event write would disable
    the resilience plane mid-failure).
    """

    def __init__(self, path: str, max_bytes: int = 16 * 1024 * 1024,
                 max_event_bytes: int = 64 * 1024):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be > 0")
        self.path = path
        self.max_bytes = int(max_bytes)
        self.max_event_bytes = int(max_event_bytes)
        self._lock = threading.Lock()
        self.write_errors = 0
        self.rotations = 0
        self.dropped_records = 0
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._size = os.path.getsize(path) if os.path.exists(path) else 0
        # per-generation record counts, tracked IN MEMORY so a rotation
        # never reads a generation file back while holding the emit
        # lock (the one-time init scan of pre-existing files is the
        # only read).  _rot1_records is what the NEXT rotation loses.
        self._gen_records = self._count_records(path)
        self._rot1_records = self._count_records(path + ".1")

    @staticmethod
    def _count_records(path: str) -> int:
        """Newline count of a generation file (one record per line) —
        used only at construction to adopt pre-existing generations.
        Bounded by max_bytes, so the read is bounded too."""
        n = 0
        try:
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        return n
                    n += chunk.count(b"\n")
        except OSError:
            return 0

    def emit(self, kind: str, **fields) -> None:
        rec = {"ts": round(time.time(), 6), "kind": kind}
        rec.update(fields)
        try:
            line = json.dumps(rec, default=_json_default) + "\n"
        except (TypeError, ValueError):
            line = json.dumps({"ts": rec["ts"], "kind": kind,
                               "error": "unserializable event"}) + "\n"
        if len(line) > self.max_event_bytes:
            line = json.dumps({"ts": rec["ts"], "kind": kind,
                               "error": "event too large",
                               "bytes": len(line)}) + "\n"
        data = line.encode("utf-8")
        with self._lock:
            if self._size + len(data) > self.max_bytes:
                # the outgoing .1 generation's records are about to be
                # discarded by the replace below — count the loss
                # (tracked in memory; no file read under this lock)
                # instead of silently dropping the tail of history
                lost = self._rot1_records
                try:
                    os.replace(self.path, self.path + ".1")
                except OSError:
                    # rotation failed (e.g. <path>.1 is a directory):
                    # appending anyway would break the byte-cap contract,
                    # and zeroing _size would break it silently — drop
                    # the event and surface the failure in the counter
                    self.write_errors += 1
                    return
                self._size = 0
                self.rotations += 1
                self.dropped_records += lost
                self._rot1_records = self._gen_records
                self._gen_records = 0
                # a rotation discards a generation of history — publish
                # it so operators learn about the loss from a scrape,
                # not from a forensics dead end (best-effort like the
                # write itself: a foreign schema conflict on the name
                # must not take down the subsystem being observed)
                try:
                    reg = get_registry()
                    reg.counter(
                        "geomx_eventlog_rotations_total",
                        "Event-log rotations (each discards the "
                        "previous rotated generation)").inc()
                    if lost:
                        reg.counter(
                            "geomx_eventlog_dropped_records_total",
                            "Event records lost when rotation discarded "
                            "the previous generation").inc(lost)
                except ValueError:
                    pass
                marker = json.dumps({"ts": rec["ts"],
                                     "kind": "rotated"}) + "\n"
                data = marker.encode("utf-8") + data
            try:
                with open(self.path, "a") as f:
                    f.write(data.decode("utf-8"))
            except OSError:
                self.write_errors += 1
                return
            self._size += len(data)
            self._gen_records += data.count(b"\n")

    def read(self) -> List[dict]:
        """Parse the current generation back (tests/diagnostics)."""
        out = []
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        out.append(json.loads(line))
        except FileNotFoundError:
            pass
        return out


def _json_default(o):
    # numpy / jax scalars land here; anything with item() flattens
    item = getattr(o, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return repr(o)


# process-global event log, configured from the environment
# (GEOMX_TELEMETRY_EVENTS=<path>; empty/unset disables) or installed
# explicitly (set_default_event_log — the GeoConfig(telemetry_events=...)
# path, so subsystems without config access, e.g. the liveness
# controller's membership transitions, land in the SAME file)
_event_log: Optional[EventLog] = None
_event_log_key: Optional[tuple] = None
_default_log: Optional[EventLog] = None
_event_log_lock = threading.Lock()


def set_default_event_log(log: Optional[EventLog]) -> None:
    """Install (or clear, with None) the process-default event log.
    Takes precedence over the env-derived one."""
    global _default_log
    with _event_log_lock:
        _default_log = log


def get_event_log() -> Optional[EventLog]:
    global _event_log, _event_log_key
    # graftlint: disable=GXL006 — config-less surface
    path = os.environ.get("GEOMX_TELEMETRY_EVENTS") or ""
    # graftlint: disable=GXL006 — config-less surface
    raw_cap = os.environ.get("GEOMX_TELEMETRY_EVENTS_MAX_BYTES") or ""
    with _event_log_lock:
        if _default_log is not None:
            return _default_log
        key = (path, raw_cap)
        if key != _event_log_key:
            if not path:
                _event_log = None
                _event_log_key = key
            else:
                # parse + construct BEFORE committing the cache key: a
                # failed init (bad cap value, uncreatable directory)
                # must raise on EVERY call, not poison the cache into
                # silently returning a stale/None log forever
                try:
                    cap = int(float(raw_cap)) if raw_cap \
                        else 16 * 1024 * 1024
                except ValueError:
                    raise ValueError(
                        "Bad value for env var "
                        f"GEOMX_TELEMETRY_EVENTS_MAX_BYTES: {raw_cap!r}")
                log = EventLog(path, max_bytes=cap)
                _event_log = log
                _event_log_key = key
        return _event_log


def log_event(kind: str, **fields) -> None:
    """Append to the configured event log; no-op when none is set."""
    log = get_event_log()
    if log is not None:
        log.emit(kind, **fields)
