"""Step-time attribution: Chrome traces -> per-step phase breakdown.

PR 5's tracing plane *collects* spans (``utils/profiler.py`` host spans,
``tracing.merge_traces`` for the cross-party view); this module
*interprets* them, following the phase-attribution methodology of
profiling-driven compression tuning ("Evaluation and Optimization of
Gradient Compression", PAPERS.md): every step window is partitioned into
four DISJOINT phases whose durations sum to the window exactly —

- ``compute``       covered by compute spans only;
- ``hidden_comms``  covered by compute AND communication (the collective
                    rides under compute — the overlap pipelining buys);
- ``exposed_comms`` covered by communication only (the step is blocked
                    on the wire — what a TSEngine-style controller must
                    shrink);
- ``host_stall``    covered by neither (input pipeline, dispatch gaps,
                    host work).

Because the partition is disjoint the four fractions sum to ~1.0 by
construction, which is the acceptance invariant ``bench.py --attribute``
gates on.

Classification is keyed on the span names/categories the repo already
records: ``train/step`` marks the step window (``Trainer.fit`` and bench
emit it), ``train/compute`` + ``kernel``-category spans
(``bsc/select_pack``, ``bsc/scatter_add``) are compute, and
``comm``-category spans (``dc_pipeline/launch``/``apply``, the bucketed
engine's ``dc_allreduce/bucket*`` spans, the host plane's
``RelayToGlobal:*`` / ``ServerPush:*`` WAN spans) are communication.
Spans matching no rule (scheduler chatter, metadata) attribute to
nothing — their time shows up as ``host_stall``, which is honest: the
step was not computing and not on the wire.

The multi-party view builds on :func:`~geomx_tpu.telemetry.tracing.
merge_traces`: :func:`attribute_merged` attributes each party's process
row separately on the shared wall-clock axis.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

PHASES = ("compute", "hidden_comms", "exposed_comms", "host_stall")

STEP_SPAN = "train/step"
COMPUTE_SPAN = "train/compute"

# span-name prefixes the host plane records for WAN communication
_COMM_NAME_PREFIXES = ("RelayToGlobal:", "RelayRowSparse:", "ServerPush:",
                       "ServerPull:", "ServerMerge:")
_COMM_NAME_PARTS = ("_pipeline/", "_allreduce/")


def classify_span(name: str, category: str = "") -> Optional[str]:
    """``"step"`` / ``"compute"`` / ``"comms"`` / None for a span.

    The rule table (first match wins):

    ==========================  =========  =============================
    match                       class      emitted by
    ==========================  =========  =============================
    name ``train/step``         step       Trainer.fit / bench
    name ``train/compute``      compute    Trainer.fit / bench
    category ``kernel``         compute    ``bsc/select_pack`` etc.
    category ``compute``        compute    any explicit compute span
    category ``comm``           comms      ``dc_pipeline/launch``,
                                           ``dc_allreduce/bucket*``,
                                           ``RelayToGlobal:*``
    name WAN prefixes/parts     comms      host-plane spans dumped
                                           without a category
    ==========================  =========  =============================
    """
    if name == STEP_SPAN or category == "step":
        return "step"
    if name == COMPUTE_SPAN or category in ("kernel", "compute"):
        return "compute"
    if category == "comm":
        return "comms"
    if name.startswith(_COMM_NAME_PREFIXES):
        return "comms"
    if any(part in name for part in _COMM_NAME_PARTS):
        return "comms"
    return None


# ---------------------------------------------------------------------------
# interval algebra
# ---------------------------------------------------------------------------

def _merge_intervals(ivs: List[Tuple[float, float]]
                     ) -> List[Tuple[float, float]]:
    """Union of [begin, end) intervals as a sorted disjoint list."""
    out: List[Tuple[float, float]] = []
    for b, e in sorted(ivs):
        if e <= b:
            continue
        if out and b <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((b, e))
    return out


def _covered(ivs: List[Tuple[float, float]]) -> float:
    return sum(e - b for b, e in ivs)


def _intersect(a: List[Tuple[float, float]],
               b: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Intersection of two disjoint sorted interval lists."""
    out: List[Tuple[float, float]] = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if lo < hi:
            out.append((lo, hi))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _clip(ivs: List[Tuple[float, float]], lo: float, hi: float
          ) -> List[Tuple[float, float]]:
    return [(max(b, lo), min(e, hi)) for b, e in ivs
            if min(e, hi) > max(b, lo)]


def attribute_window(window: Tuple[float, float],
                     compute: List[Tuple[float, float]],
                     comms: List[Tuple[float, float]]) -> Dict[str, float]:
    """Partition one step window into the four disjoint phase durations
    (microseconds, same unit as Chrome trace timestamps)."""
    lo, hi = window
    total = max(hi - lo, 0.0)
    cmp_u = _merge_intervals(_clip(compute, lo, hi))
    com_u = _merge_intervals(_clip(comms, lo, hi))
    hidden = _covered(_intersect(cmp_u, com_u))
    compute_only = _covered(cmp_u) - hidden
    exposed = _covered(com_u) - hidden
    stall = total - compute_only - hidden - exposed
    return {"compute": compute_only, "hidden_comms": hidden,
            "exposed_comms": exposed, "host_stall": max(stall, 0.0),
            "total": total}


# ---------------------------------------------------------------------------
# trace-level attribution
# ---------------------------------------------------------------------------

def _duration_events(doc: dict) -> List[dict]:
    return [ev for ev in doc.get("traceEvents", [])
            if ev.get("ph") == "X" and "ts" in ev and "dur" in ev]


def attribute_trace(doc: dict, pid: Optional[int] = None,
                    extend_to_next: bool = True,
                    since_us: Optional[float] = None) -> Dict[str, Any]:
    """Attribute a Chrome trace document into per-step phase breakdowns.

    ``doc``: a loaded trace (``Profiler.dump`` output or one process row
    of a merged trace — restrict with ``pid``).  Step windows come from
    ``train/step`` spans; with ``extend_to_next`` (default) each window
    runs to the NEXT step's start so the inter-step gap (input pipeline,
    host loop) is attributed as ``host_stall`` instead of vanishing
    between windows — the last step keeps its own span length.
    ``since_us`` drops spans starting before that trace timestamp — the
    window-scoping hook for a long-lived process whose global profiler
    accumulates across fits (mark ``Profiler.now_us()`` at the window
    start, attribute only what this window recorded).

    Returns ``{"steps": [per-step dicts], "summary": {phase ->
    fraction}, "totals_us": {phase -> us}, "num_steps": N}``; the four
    summary fractions sum to ~1.0 whenever any step was found.
    """
    steps_spans: List[dict] = []
    compute: List[Tuple[float, float]] = []
    comms: List[Tuple[float, float]] = []
    for ev in _duration_events(doc):
        if pid is not None and ev.get("pid") != pid:
            continue
        if since_us is not None and float(ev["ts"]) < since_us:
            continue
        kind = classify_span(ev.get("name", ""), ev.get("cat", ""))
        iv = (float(ev["ts"]), float(ev["ts"]) + float(ev["dur"]))
        if kind == "step":
            steps_spans.append(ev)
        elif kind == "compute":
            compute.append(iv)
        elif kind == "comms":
            comms.append(iv)

    steps_spans.sort(key=lambda e: e["ts"])
    steps: List[Dict[str, Any]] = []
    for i, ev in enumerate(steps_spans):
        lo = float(ev["ts"])
        hi = lo + float(ev["dur"])
        if extend_to_next and i + 1 < len(steps_spans):
            hi = max(hi, float(steps_spans[i + 1]["ts"]))
        rec = attribute_window((lo, hi), compute, comms)
        rec["step"] = (ev.get("args") or {}).get("step", i)
        steps.append(rec)

    totals = {ph: sum(s[ph] for s in steps) for ph in PHASES}
    grand = sum(totals.values())
    summary = {ph: (totals[ph] / grand if grand else 0.0) for ph in PHASES}
    return {"steps": steps, "summary": summary, "totals_us": totals,
            "num_steps": len(steps)}


def attribute_merged(traces: Sequence[Any],
                     labels: Optional[Sequence[str]] = None
                     ) -> Dict[str, Any]:
    """Multi-party attribution on one shared timeline: merge N parties'
    trace dumps (``merge_traces`` — wall-clock aligned) and attribute
    each party's process row separately.  Returns ``{"parties": {label:
    attribution}, "merged": <merged trace doc>}``."""
    from geomx_tpu.telemetry.tracing import merge_traces, process_names
    merged = merge_traces(traces, labels=labels)
    names = process_names(merged)
    parties = {}
    for pid in sorted(names):
        att = attribute_trace(merged, pid=pid)
        if att["num_steps"] or any(att["totals_us"].values()):
            parties[names[pid]] = att
    return {"parties": parties, "merged": merged}


def publish_attribution(summary: Dict[str, float], registry=None) -> None:
    """Publish a phase-fraction summary as registry gauges
    (``geomx_phase_fraction{phase=...}``) — the scheduler's ``/metrics``
    surface then exports the live breakdown."""
    from geomx_tpu.telemetry.registry import get_registry
    reg = registry if registry is not None else get_registry()
    fam = reg.gauge("geomx_phase_fraction",
                    "Step-time fraction per attributed phase", ("phase",))
    for ph in PHASES:
        fam.labels(phase=ph).set(float(summary.get(ph, 0.0)))
