"""Unified telemetry plane (docs/telemetry.md).

- :mod:`registry` — process-global Counter/Gauge/Histogram registry
  every subsystem writes into;
- :mod:`probes` — in-graph step-health probes (grad norm, NaN/Inf,
  achieved compression, EF residuals), gated by ``GEOMX_TELEMETRY``
  with a jaxpr-identical disabled path;
- :mod:`tracing` — cross-party WAN round correlation (``round_id``
  spans + :func:`merge_traces`);
- :mod:`export` — Prometheus text exposition and the bounded JSONL
  event log.
"""

from geomx_tpu.telemetry.export import (EventLog, get_event_log, log_event,
                                        parse_prometheus_text,
                                        render_prometheus)
from geomx_tpu.telemetry.probes import telemetry_enabled
from geomx_tpu.telemetry.registry import (MetricRegistry, get_registry,
                                          reset_registry)
from geomx_tpu.telemetry.tracing import merge_traces, rounds_in_trace

__all__ = [
    "MetricRegistry", "get_registry", "reset_registry",
    "telemetry_enabled",
    "EventLog", "get_event_log", "log_event",
    "render_prometheus", "parse_prometheus_text",
    "merge_traces", "rounds_in_trace",
]
