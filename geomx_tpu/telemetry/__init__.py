"""Unified telemetry plane (docs/telemetry.md).

Sensors:

- :mod:`registry` — process-global Counter/Gauge/Histogram registry
  every subsystem writes into;
- :mod:`probes` — in-graph step-health probes (grad norm, NaN/Inf,
  achieved compression, EF residuals), gated by ``GEOMX_TELEMETRY``
  with a jaxpr-identical disabled path;
- :mod:`tracing` — cross-party WAN round correlation (``round_id``
  spans + :func:`merge_traces`);
- :mod:`export` — Prometheus text exposition and the bounded JSONL
  event log.

Interpretation (the step-time observatory, built on the sensors):

- :mod:`attribution` — Chrome traces -> per-step compute / hidden-comms
  / exposed-comms / host-stall phase breakdown;
- :mod:`roofline` — MFU, arithmetic intensity and a compute/memory/
  wire bound verdict from ``compiled.cost_analysis()`` + wire
  accounting;
- :mod:`links` — per-(party, peer) EWMA throughput/RTT/loss estimates
  from replayed WAN round spans (:class:`LinkObservatory`);
- :mod:`flight` — bounded per-step flight recorder with deterministic
  anomaly rules and forensics bundles (``GEOMX_FLIGHT``).

Whole-run capture (built on all of the above):

- :mod:`capsule` — run capsules: one versioned archive of the whole
  observability state with bit-exact offline replay
  (``GEOMX_CAPSULE``, ``tools/runcap.py``);
- :mod:`costmodel` — a step-time cost model fitted from capsule
  records for offline what-if search over candidate configs.
"""

from geomx_tpu.telemetry.attribution import (attribute_merged,
                                             attribute_trace,
                                             classify_span,
                                             publish_attribution)
from geomx_tpu.telemetry.capsule import (Capsule, RegistrySampler,
                                         RunCapsule, capsule_enabled,
                                         capsule_from_config,
                                         sample_registry)
from geomx_tpu.telemetry.costmodel import (StepTimeCostModel,
                                           candidate_wire_bytes,
                                           fit_affine_link)
from geomx_tpu.telemetry.export import (EventLog, get_event_log, log_event,
                                        parse_prometheus_text,
                                        render_prometheus)
from geomx_tpu.telemetry.flight import (FlightRecorder, flight_enabled,
                                        flight_recorder_from_config,
                                        install_incident_recorder,
                                        notify_host_incident,
                                        uninstall_incident_recorder)
from geomx_tpu.telemetry.ledger import (RoundLedger, get_round_ledger,
                                        reset_round_ledger)
from geomx_tpu.telemetry.links import (LinkObservatory,
                                       get_link_observatory,
                                       reset_link_observatory)
from geomx_tpu.telemetry.probes import telemetry_enabled
from geomx_tpu.telemetry.registry import (MetricRegistry, get_registry,
                                          reset_registry)
from geomx_tpu.telemetry.roofline import (publish_roofline, roofline_record,
                                          trainer_roofline)
from geomx_tpu.telemetry.tracing import merge_traces, rounds_in_trace

__all__ = [
    "MetricRegistry", "get_registry", "reset_registry",
    "telemetry_enabled",
    "EventLog", "get_event_log", "log_event",
    "render_prometheus", "parse_prometheus_text",
    "merge_traces", "rounds_in_trace",
    "attribute_trace", "attribute_merged", "classify_span",
    "publish_attribution",
    "roofline_record", "trainer_roofline", "publish_roofline",
    "LinkObservatory", "get_link_observatory", "reset_link_observatory",
    "RoundLedger", "get_round_ledger", "reset_round_ledger",
    "FlightRecorder", "flight_enabled", "flight_recorder_from_config",
    "notify_host_incident", "install_incident_recorder",
    "uninstall_incident_recorder",
    "RunCapsule", "Capsule", "RegistrySampler", "sample_registry",
    "capsule_enabled", "capsule_from_config",
    "StepTimeCostModel", "fit_affine_link", "candidate_wire_bytes",
]
