"""Process-global metric registry: Counters, Gauges, Histograms.

The unified telemetry plane's write side.  Every subsystem (sync tiers,
host-plane servers, scheduler, resilience controller, trainer) records
into one process-global :class:`MetricRegistry`; the read side is the
Prometheus text exposition in :mod:`geomx_tpu.telemetry.export` (served
from the scheduler's HTTP endpoint and ``COMMAND {cmd:"metrics"}`` on
``GeoPSServer``).

Design points, in the spirit of prometheus_client but dependency-free:

- a *family* is (name, help, type, label names); ``labels(...)`` binds a
  label-value tuple to a *child* carrying the actual number.  Families
  are idempotent to re-register (same type + labels required), so every
  call site can say ``get_registry().counter("x", ...)`` without
  coordinating module import order;
- children are cached — hot paths bind once and call ``inc()``/
  ``set()``/``observe()`` on the bound child (a dict hit + one lock);
- everything is thread-safe: the host plane records from server handler
  threads, relay shards, heartbeat loops and the training loop at once.

Metric and label names follow the Prometheus data model
(``[a-zA-Z_:][a-zA-Z0-9_:]*`` / ``[a-zA-Z_][a-zA-Z0-9_]*``); the
registry rejects invalid names at registration so a typo fails at the
call site, not in the scrape.
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# prometheus_client's default histogram buckets (seconds-oriented, which
# suits the host plane's RPC latencies); callers with other units pass
# their own
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)


class _Child:
    """One labeled series.  Subclasses add the type-specific mutators."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class CounterChild(_Child):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (inc by {amount})")
        with self._lock:
            self._value += amount


class GaugeChild(_Child):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount


class HistogramChild(_Child):
    def __init__(self, buckets: Sequence[float]):
        super().__init__()
        self.upper_bounds = tuple(buckets)
        self.bucket_counts = [0] * (len(self.upper_bounds) + 1)  # +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.sum += value
            self.count += 1
            for i, ub in enumerate(self.upper_bounds):
                if value <= ub:
                    self.bucket_counts[i] += 1
                    break
            else:
                self.bucket_counts[-1] += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count) — the
        cumulative form the exposition format wants."""
        with self._lock:
            cum, acc = [], 0
            for c in self.bucket_counts:
                acc += c
                cum.append(acc)
            return cum, self.sum, self.count

    def percentile(self, q: float) -> float:
        """Linear-interpolated estimate from the bucket boundaries (for
        in-process summaries; the scrape side gets the raw buckets)."""
        cum, _s, count = self.snapshot()
        if count == 0:
            return math.nan
        target = q * count
        lo = 0.0
        for i, ub in enumerate(self.upper_bounds):
            if cum[i] >= target:
                prev = cum[i - 1] if i else 0
                frac = (target - prev) / max(cum[i] - prev, 1)
                return lo + (ub - lo) * frac
            lo = ub
        return self.upper_bounds[-1] if self.upper_bounds else math.nan


_CHILD_TYPES = {"counter": CounterChild, "gauge": GaugeChild,
                "histogram": HistogramChild}


class MetricFamily:
    def __init__(self, name: str, help: str, type: str,
                 label_names: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_NAME_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r} for {name}")
        if type not in _CHILD_TYPES:
            raise ValueError(f"unknown metric type {type!r}")
        self.name = name
        self.help = help
        self.type = type
        self.label_names = tuple(label_names)
        self.buckets = tuple(sorted(set(float(b) for b in buckets)))
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        if not self.label_names:
            # unlabeled metric: one implicit child, usable directly
            self._children[()] = self._make_child()

    def _make_child(self) -> _Child:
        if self.type == "histogram":
            return HistogramChild(self.buckets)
        return _CHILD_TYPES[self.type]()

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "keyword, not both")
            try:
                values = tuple(kv[n] for n in self.label_names)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e.args[0]!r} "
                    f"(labels: {self.label_names})")
            if set(kv) - set(self.label_names):
                raise ValueError(
                    f"{self.name}: unknown label(s) "
                    f"{sorted(set(kv) - set(self.label_names))}")
        values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: got {len(values)} label values for "
                f"{len(self.label_names)} labels {self.label_names}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._make_child()
            return child

    def children(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())

    # unlabeled convenience: family acts as its own single child
    def _solo(self):
        if self.label_names:
            raise ValueError(
                f"{self.name} has labels {self.label_names}; bind with "
                ".labels(...) first")
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def observe(self, value: float) -> None:
        self._solo().observe(value)


class MetricRegistry:
    """Name -> family table.  Registration is idempotent when the
    (type, label set) agree; a conflicting re-registration raises —
    two subsystems silently sharing a name with different schemas is a
    bug worth failing on."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}
        self.created_unix = time.time()

    def _register(self, name: str, help: str, type: str,
                  labels: Sequence[str], buckets=DEFAULT_BUCKETS
                  ) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                want_buckets = tuple(sorted(set(float(b)
                                                for b in buckets)))
                if fam.type != type or fam.label_names != tuple(labels) \
                        or (type == "histogram"
                            and fam.buckets != want_buckets):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"schema: existing ({fam.type}, {fam.label_names}"
                        f"{', buckets ' + str(fam.buckets) if fam.type == 'histogram' else ''})"
                        f" vs new ({type}, {tuple(labels)})")
                return fam
            fam = MetricFamily(name, help, type, labels, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> MetricFamily:
        return self._register(name, help, "counter", labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> MetricFamily:
        return self._register(name, help, "gauge", labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> MetricFamily:
        return self._register(name, help, "histogram", labels, buckets)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def collect(self) -> Iterable[MetricFamily]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def clear(self) -> None:
        """Drop every family (test isolation)."""
        with self._lock:
            self._families.clear()


# the process-global registry every subsystem writes into
_registry = MetricRegistry()
_registry_lock = threading.Lock()


def get_registry() -> MetricRegistry:
    return _registry


def reset_registry() -> MetricRegistry:
    """Clear the global registry (tests); the object identity is kept so
    already-bound families go stale rather than resurrect — re-bind via
    get_registry() after a reset."""
    _registry.clear()
    return _registry
