"""MixedSync — asynchronous global tier, with optional DCASGD compensation.

Reference semantics (README.md:36-40): the intra-party tier stays
synchronous, but local servers push to the global tier without a barrier
(DataHandleAsyncDefault, kvstore_dist_server.h:1532-1625); the global
optimizer applies each party's gradient as it arrives, so a party's
gradient is computed at weights that are stale by the other parties'
in-flight updates.  DCASGD (python/mxnet/optimizer/optimizer.py:872-925)
compensates: for gradient g pushed from stale weights w_stale applied at
current weights w,

    g_compensated = g + lambda * g * g * (w - w_stale).

TPU-native emulation inside one SPMD program: true weights evolve
deterministically on every device; each party holds a *stale copy* it
computes gradients at, refreshed every ``pull_interval`` steps (the
asynchronous pull).  Each step the global update applies the sum of all
parties' delay-compensated gradients — the batched equivalent of the
reference's arrival-ordered sequence of async applies.  ``pull_interval``
plays the role of the reference's effective staleness (its async tier has
staleness ~1 round).  For exact multi-process asynchrony across hosts, the
host-side parameter service in ``geomx_tpu.store`` is the escape hatch.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from geomx_tpu.compression.base import Compressor, NoCompressor
from geomx_tpu.sync.base import SyncAlgorithm
from geomx_tpu.topology import DC_AXIS, WORKER_AXIS


class MixedSync(SyncAlgorithm):
    name = "mixed"
    supports_degraded = True  # renormalized survivor mean (resilience/)
    grads_replicated_after_sync = True  # hierarchical psum output
    supports_zero = True  # bucket-shard form (train/zero.py)

    def __init__(self, dc_compressor: Optional[Compressor] = None,
                 pull_interval: int = 1, dcasgd_lambda: float = 0.0,
                 bucket_bytes: Optional[int] = None):
        if pull_interval < 1:
            raise ValueError("pull_interval must be >= 1")
        from geomx_tpu.compression.bucketing import maybe_bucketed
        # same dc-tier default as FSA: fused flat-bucket collectives
        # (GEOMX_BUCKET_BYTES=0 opts out)
        self.dc_compressor = maybe_bucketed(dc_compressor or NoCompressor(),
                                            bucket_bytes)
        self.pull_interval = int(pull_interval)
        self.dcasgd_lambda = float(dcasgd_lambda)

    def _dc_init(self, params: Any) -> Any:
        if self.zero_plan is not None:
            return self.dc_compressor.init_shard_state(params,
                                                       self.zero_plan.W)
        return self.dc_compressor.init_state(params)

    def init_state(self, params: Any, model_state: Any = None) -> Any:
        # the stale pull copy stays FULL and replicated even under ZeRO:
        # it is what the forward pass runs at (forward_params), not an
        # update-side buffer
        return {
            "stale": jax.tree.map(jnp.asarray, params),
            "dc_comp": self._dc_init(params),
        }

    def forward_params(self, params: Any, state: Any) -> Any:
        # parties train at their stale pull of the global weights
        return state["stale"]

    def sync_grads(self, grads: Any, params: Any, state: Any,
                   step: jax.Array) -> Tuple[Any, Any]:
        nw = self.workers_per_party
        # intra-party tier stays synchronous (dist_async still merges the
        # party's workers at the local server before the global push)
        if nw > 1:
            grads = jax.tree.map(lambda g: lax.pmean(g, WORKER_AXIS), grads)
        if self.dcasgd_lambda > 0.0:
            lam = self.dcasgd_lambda
            grads = jax.tree.map(
                lambda g, w, ws: g + lam * g * g * (w - ws),
                grads, params, state["stale"])
        # degraded mode (resilience/): exclude dead parties' shards and
        # renormalize the mean over survivors — same algebra as FSA
        w = self.party_weight()
        if w is not None:
            grads = jax.tree.map(lambda g: g * w, grads)
        np_ = self.num_parties
        grads, dstate = self.dc_compressor.allreduce(
            grads, state["dc_comp"], DC_AXIS, np_)
        nl = self.num_live
        if nl > 1:  # single-survivor configs skip the dead g/1 divide
            grads = jax.tree.map(lambda g: g / nl, grads)
        state = dict(state, dc_comp=dstate)
        return grads, state

    def sync_grad_shards(self, grads: Any, params: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        """ZeRO form of :meth:`sync_grads` (train/zero.py): worker-tier
        psum_scatter on the fused buckets, DCASGD compensation computed
        shard-wise against this worker's slice of the true and stale
        weights (both replicated, so the slice is free), then the
        per-shard compressed dc tier with the survivor-mean algebra."""
        plan = self.zero_plan
        leaves = jax.tree.leaves(grads)
        bk = self.dc_compressor.zero_bucketer(leaves)
        shards = [plan.scatter_bucket(b, WORKER_AXIS)
                  for b in bk.flatten(leaves)]
        if self.dcasgd_lambda > 0.0:
            lam = self.dcasgd_lambda
            widx = lax.axis_index(WORKER_AXIS)
            p_sh = plan.tree_shards(params, bk, widx)
            s_sh = plan.tree_shards(state["stale"], bk, widx)
            shards = [g + lam * g * g * (w - ws)
                      for g, w, ws in zip(shards, p_sh, s_sh)]
        w = self.party_weight()
        if w is not None:
            shards = [g * w for g in shards]
        shards, dstate = self.dc_compressor.allreduce_shards(
            shards, state["dc_comp"], DC_AXIS, self.num_parties, bk)
        nl = self.num_live
        if nl > 1:
            shards = [g / nl for g in shards]
        return shards, dict(state, dc_comp=dstate)

    def sync_params(self, params: Any, state: Any,
                    step: jax.Array) -> Tuple[Any, Any]:
        # the asynchronous pull: refresh the stale copy every pull_interval
        do_pull = ((step + 1) % self.pull_interval) == 0
        stale = lax.cond(do_pull, lambda _: params, lambda s: s, state["stale"])
        return params, dict(state, stale=stale)

    def sync_model_state(self, model_state: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        if not jax.tree.leaves(model_state):
            return model_state, state
        if self.workers_per_party > 1:
            model_state = lax.pmean(model_state, WORKER_AXIS)
        if self.num_parties > 1:
            w = self.party_weight()
            if w is None:
                model_state = lax.pmean(model_state, DC_AXIS)
            else:
                nl = self.num_live
                model_state = jax.tree.map(
                    lambda x: lax.psum(x * w, DC_AXIS) / nl, model_state)
        return model_state, state

    def reset_comm_state(self, params: Any, state: Any,
                         policy: str = "reset") -> Any:
        """Same policy as FSA: "reset" re-initializes dc-tier compressor
        state; the stale-pull copy always carries (it tracks the true
        weights, which survive a membership change unchanged)."""
        state = super().reset_comm_state(params, state, policy)
        if policy == "carry":
            return state
        return dict(state, dc_comp=self._dc_init(params))

    def telemetry_scalars(self, state: Any) -> dict:
        """EF residual magnitude plus the staleness gap: the distance
        between the true weights' last refresh and the stale copy the
        party trains at is exactly the drift DCASGD compensates —
        watching it catch a mis-set pull_interval in situ
        (telemetry/probes.py; enabled-path only)."""
        from geomx_tpu.telemetry.probes import tree_norm
        return {"ef_residual_norm": tree_norm(state["dc_comp"]),
                "stale_copy_norm": tree_norm(state["stale"])}
