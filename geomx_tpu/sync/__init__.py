"""Synchronization algorithms over the two HiPS tiers.

Reference suite (README.md:32-45): FSA (fully-synchronous, default),
MixedSync (asynchronous global tier, optional DCASGD delay compensation),
HFA (hierarchical frequency aggregation).  ESync is documented by the
reference as "to be integrated" and has no implementation there
(SURVEY.md "What the reference is"); we match that scope.

Each algorithm is a set of pure hooks called inside the SPMD train step;
algorithm state (milestones, stale copies, compressor residuals) is
device-local party state threaded through the TrainState.
"""

from geomx_tpu.sync.base import SyncAlgorithm
from geomx_tpu.sync.dgt import DGTCompressor
from geomx_tpu.sync.fsa import FSA
from geomx_tpu.sync.hfa import HFA
from geomx_tpu.sync.mixed import MixedSync
from geomx_tpu.sync.pipeline import PipelinedSync

__all__ = ["SyncAlgorithm", "FSA", "HFA", "MixedSync", "DGTCompressor",
           "PipelinedSync", "get_sync_algorithm"]


def get_sync_algorithm(cfg, compressor=None):
    """Build the sync algorithm named by ``cfg.sync_mode`` from a GeoConfig."""
    from geomx_tpu.compression import get_compressor
    comp = compressor if compressor is not None else get_compressor(cfg.compression)
    if cfg.enable_dgt:
        comp = DGTCompressor(inner=comp, block_elems=max(1, cfg.dgt_block_size // 4),
                             k=cfg.dgt_k, alpha=cfg.dgt_contri_alpha,
                             channels=cfg.udp_channel_num)
    mode = cfg.sync_mode.lower()
    bucket_bytes = getattr(cfg, "bucket_bytes", None)
    if mode in ("fsa", "dist_sync", "sync"):
        algo = FSA(dc_compressor=comp, bucket_bytes=bucket_bytes)
    elif mode in ("mixed", "dist_async", "async"):
        # DCASGD compensation is opt-in (reference: --dcasgd flag selects it;
        # plain --mixed-sync runs the uncompensated optimizer)
        lam = cfg.dcasgd_lambda if getattr(cfg, "dcasgd", False) else 0.0
        algo = MixedSync(dc_compressor=comp,
                         pull_interval=cfg.mixed_pull_interval,
                         dcasgd_lambda=lam,
                         bucket_bytes=bucket_bytes)
    elif mode == "hfa":
        algo = HFA(k1=cfg.hfa_k1, k2=cfg.hfa_k2, dc_compressor=comp,
                   bucket_bytes=bucket_bytes)
    else:
        raise ValueError(f"Unknown sync mode: {cfg.sync_mode!r}")
    depth = getattr(cfg, "pipeline_depth", 0)
    if depth and cfg.num_parties <= 1:
        # same single-axis elision policy as the x/1 divide guards and
        # HFA's one-party milestone skip: with one party there is no
        # dc-tier round trip to hide, and staleness-1 would only degrade
        # the trajectory (a cluster launch script's exported
        # GEOMX_PIPELINE_DEPTH must not taint a 1-party debug run)
        import warnings
        warnings.warn(
            "GEOMX_PIPELINE_DEPTH ignored: num_parties == 1 has no "
            "dc-tier collective to pipeline", stacklevel=2)
    elif depth:
        # opt-in pipelined WAN sync: double-buffer the dc-tier collective
        # so the DCN round trip overlaps the next step's compute
        # (sync/pipeline.py); rejects HFA loudly inside the constructor
        algo = PipelinedSync(algo, depth=depth,
                             dcasgd_lambda=getattr(cfg, "pipeline_dcasgd",
                                                   0.0))
    return algo
