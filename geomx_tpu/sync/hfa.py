"""HFA — Hierarchical Frequency Aggregation.

Reference semantics (README.md:41-44; worker loop examples/cnn_hfa.py:108-134;
server milestone math kvstore_dist_server.h:988-1017,1327-1346):

- every step: each worker runs its *own* optimizer update (params drift);
- every K1 steps: workers push ``params / num_local_workers`` and pull — the
  local tier averages parameters within the party;
- every K2 local syncs (i.e. every K1*K2 steps): the local server pushes
  ``(store - milestone) / num_parties`` — the parameter *delta* since the
  last global milestone — the global server sets
  ``store = milestone + sum(deltas)`` and everyone resets their milestone.

Net effect: two-frequency hierarchical parameter averaging.  The milestone
is not redundant once the global delta is compressed (Bi-Sparse over HFA):
unsent delta mass stays in the compressor residuals relative to the
milestone, exactly as in the reference's compressed-HFA path
(kvstore_dist_server.h:1334-1338).

TPU-native: parameters live per-device (replica axes), the K1 hook is a
``pmean`` over the worker axis, the K1*K2 hook a compressed all-reduce of
deltas over the dc axis, both gated by ``lax.cond`` so skipped steps cost
nothing on the wire.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from geomx_tpu.compression.base import Compressor, NoCompressor
from geomx_tpu.sync.base import SyncAlgorithm
from geomx_tpu.topology import DC_AXIS, WORKER_AXIS


class HFA(SyncAlgorithm):
    name = "hfa"

    def __init__(self, k1: int = 20, k2: int = 10,
                 dc_compressor: Optional[Compressor] = None,
                 bucket_bytes: Optional[int] = None):
        if k1 < 1 or k2 < 1:
            raise ValueError("HFA periods must be >= 1")
        from geomx_tpu.compression.bucketing import maybe_bucketed
        self.k1 = int(k1)
        self.k2 = int(k2)
        # the K1*K2 global delta crosses the same WAN hop as FSA's
        # gradients, so it gets the same fused flat-bucket default: one
        # compressed collective per bucket instead of per leaf
        # (GEOMX_BUCKET_BYTES=0 opts out).  Exact for the dense default
        # (the bucket layout is a permutation and the padding is zeros).
        self.dc_compressor = maybe_bucketed(dc_compressor or NoCompressor(),
                                            bucket_bytes)

    def init_state(self, params: Any, model_state: Any = None) -> Any:
        if self.num_parties <= 1:
            # one party: the global tier never fires (the Python gate in
            # sync_params), so a milestone copy + compressor state would
            # be dead weight threaded through every dispatch — this plus
            # the per-leaf DGT schedule (sync/dgt.py module docstring)
            # together measured +4.5 ms/step at 1x1 on a tunneled chip
            # (BENCH_CAPTURED_r04: hfa_dgt 18.2 ms vs vanilla 13.7 ms,
            # where HFA computes nothing at all)
            return {}
        return {
            # last globally-agreed parameters (reference stored_milestone)
            "milestone": jax.tree.map(jnp.asarray, params),
            "dc_comp": self.dc_compressor.init_state(params),
        }

    # gradients are applied locally — no per-step gradient communication
    # (that is the point of HFA: sync frequency decoupled from step frequency)

    def sync_params(self, params: Any, state: Any,
                    step: jax.Array) -> Tuple[Any, Any]:
        # `step` is the 0-based step being finished; the reference gates on
        # 1-based global_iters % K1 == 0 (cnn_hfa.py:119)
        iters = step + 1
        do_local = (iters % self.k1) == 0
        do_global = (iters % (self.k1 * self.k2)) == 0

        if self.workers_per_party > 1:
            def local_sync(p):
                return lax.pmean(p, WORKER_AXIS)
            params = lax.cond(do_local, local_sync, lambda p: p, params)

        def global_sync(operand):
            p, st = operand
            milestone = st["milestone"]
            # per-party delta, pre-divided as the reference does
            # ((store - milestone)/NumGlobalWorkers, kvstore_dist_server.h:1334)
            delta = jax.tree.map(
                lambda a, m: (a - m) / self.num_parties, p, milestone)
            agg, comp_state = self.dc_compressor.allreduce(
                delta, st["dc_comp"], DC_AXIS, self.num_parties)
            new_p = jax.tree.map(lambda m, d: m + d, milestone, agg)
            return new_p, {"milestone": new_p, "dc_comp": comp_state}

        def no_global(operand):
            p, st = operand
            return p, st

        if self.num_parties > 1:
            params, state = lax.cond(do_global, global_sync, no_global,
                                     (params, state))
        return params, state

    def sync_model_state(self, model_state: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        if not jax.tree.leaves(model_state):
            return model_state, state
        iters = step + 1
        if self.workers_per_party > 1:
            model_state = lax.cond(
                (iters % self.k1) == 0,
                lambda s: lax.pmean(s, WORKER_AXIS), lambda s: s, model_state)
        if self.num_parties > 1:
            model_state = lax.cond(
                (iters % (self.k1 * self.k2)) == 0,
                lambda s: lax.pmean(s, DC_AXIS), lambda s: s, model_state)
        return model_state, state
