"""DGT — Differential Gradient Transmission, TPU-native re-expression.

Reference semantics (kv_app.h:1088-1196, van.cc:723-846): the push to the
global tier is sliced into fixed-size blocks; each block's *contribution*
is an EWMA of its mean |gradient|
(``contri = alpha*contri + (1-alpha)*mean|block|``, Evaluate_msg_contri,
kv_app.h:1047-1068); blocks are ranked by contribution, the top
``round(k * nblocks)`` go over reliable TCP (channel 0), the rest over N
UDP channels with descending DSCP priority (Get_channel, kv_app.h:1071-1086)
— i.e. less-important gradient blocks may arrive late (or, rarely, not at
all) without stalling the step.

On TPU there is no lossy channel and no DSCP; the *performance* content of
DGT — only the important fraction of the gradient is on the critical path,
the rest is delivered off the critical path — maps to a deferred-aggregation
schedule:

- top-k-by-contribution blocks are all-reduced immediately (channel 0);
- the remaining blocks accumulate into a device-local ``pending`` buffer
  (the in-flight UDP payload) and are delivered when either (a) their block
  becomes important, or (b) a periodic drain every ``channels`` steps fires
  (modelling the lower-priority channels' longer delivery time).

No gradient mass is ever dropped — matching DGT-with-reliable-resend
(Resender, ps-lite src/resender.h) rather than its lossiest configuration,
which is the convergence-safe choice.

Composes as a Compressor so DGT stacks under any sync algorithm and over
any inner wire compressor, mirroring ENABLE_DGT being orthogonal to the
sync mode in the reference.

TPU cost model (round-5 rework): the tree-level ``allreduce`` flattens
the WHOLE gradient pytree into one contiguous fp32 vector and runs the
deferral schedule once — one contribution EWMA, one top-k, one pending
read-modify-write, one inner all-reduce — instead of per-leaf.  Per-leaf
DGT on a ~25-leaf model meant ~25 tiny sorts + 100 extra state buffers
threaded through every dispatch; round 4 measured the combined cost of
that plus HFA's dead milestone carriage as +4.5 ms/step at 1x1
(BENCH_CAPTURED_r04 hfa_dgt 18.2 ms vs vanilla 13.7 ms, where no sync
runs at all — both sources fixed together in round 5, so the split
between them was never measured separately).  Ranking is therefore
GLOBAL across the model's blocks
rather than per-tensor; the reference ranks within each pushed key
(kv_app.h:1088-1196), but its k is the same fraction everywhere, so the
amortized wire volume is identical and global ordering is strictly
better at picking the important mass.  ``allreduce_leaf`` keeps the
exact per-leaf schedule for single-tensor callers and tests.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from geomx_tpu.compression.base import Compressor, NoCompressor


class DGTCompressor(Compressor):
    name = "dgt"
    # the tree-level allreduce below already fuses the whole gradient into
    # one flat buffer — the bucketing default must not wrap it again
    fuses_tree = True

    def __init__(self, inner: Optional[Compressor] = None,
                 block_elems: int = 1024, k: float = 0.5, alpha: float = 0.3,
                 channels: int = 1, k_min: float = 0.2, adaptive: bool = False):
        # defaults mirror kv_app.h:1036-1045 (DGT_BLOCK_SIZE=4096 bytes,
        # DMLC_K=0.5, DMLC_K_MIN=0.2, DGT_CONTRI_ALPHA=0.3,
        # DMLC_UDP_CHANNEL_NUM=1).  k_min/adaptive are accepted for config
        # parity: the reference parses ADAPTIVE_K_FLAG/DMLC_K_MIN
        # (kv_app.h:1041-1042) but never acts on them — dmlc_k is reset to
        # dmlc_k_init before every send (kv_app.h:1118,1228,1341) — so
        # matching behavior is a fixed k.
        self.inner = inner or NoCompressor()
        self.block_elems = max(1, int(block_elems))
        self.k = float(k)
        self.k_min = float(k_min)
        self.alpha = float(alpha)
        self.flush_every = max(1, int(channels))
        self.adaptive = adaptive

    def _nblocks(self, n: int) -> int:
        return -(-n // self.block_elems)

    def init_leaf_state(self, leaf: jax.Array) -> Any:
        nb = self._nblocks(leaf.size)
        return {
            "contri": jnp.zeros((nb,), jnp.float32),
            "pending": jnp.zeros((nb * self.block_elems,), jnp.float32),
            "step": jnp.zeros((), jnp.int32),
            "inner": self.inner.init_leaf_state(leaf),
        }

    def _defer_schedule(self, gf: jax.Array, state: Any):
        """The DGT core on one flat fp32 vector padded to whole blocks:
        returns (sendable flat vector, new state sans 'inner')."""
        nb = gf.shape[0] // self.block_elems
        blocks = (gf + state["pending"]).reshape(nb, self.block_elems)

        # contribution EWMA over mean |g| per block (kv_app.h:1058-1066)
        mag = jnp.mean(jnp.abs(gf.reshape(nb, self.block_elems)), axis=1)
        contri = self.alpha * state["contri"] + (1.0 - self.alpha) * mag

        # channel 0 = top round(k * nblocks) blocks (Get_channel min_index)
        k_now = max(1, int(round(self.k * nb)))
        if k_now >= nb:
            send_mask = jnp.ones((nb,), bool)
        else:
            kth = lax.top_k(contri, k_now)[0][-1]
            send_mask = contri >= kth
        # periodic drain of the deferred channels
        step = state["step"]
        drain = (step + 1) % self.flush_every == 0
        send_mask = jnp.logical_or(send_mask, drain)

        sendable = jnp.where(send_mask[:, None], blocks, 0.0).reshape(-1)
        pending = jnp.where(send_mask[:, None], 0.0, blocks).reshape(-1)
        return sendable, {"contri": contri, "pending": pending,
                          "step": step + 1}

    def allreduce_leaf(self, g: jax.Array, state: Any, axis_name: str,
                       axis_size: int) -> Tuple[jax.Array, Any]:
        shape, dtype, n = g.shape, g.dtype, g.size
        padded = self._nblocks(n) * self.block_elems
        gf = jnp.zeros((padded,), jnp.float32).at[:n].set(
            g.reshape(-1).astype(jnp.float32))
        sendable, new_state = self._defer_schedule(gf, state)
        summed, inner_state = self.inner.allreduce_leaf(
            sendable[:n].reshape(shape).astype(dtype),
            state["inner"], axis_name, axis_size)
        new_state["inner"] = inner_state
        return summed, new_state

    # -- tree-level fast path (see module docstring: one schedule for the
    # -- whole gradient instead of one per leaf) ---------------------------
    def init_state(self, grads: Any) -> Any:
        n = sum(leaf.size for leaf in jax.tree.leaves(grads))
        padded = self._nblocks(n) * self.block_elems
        flat = jnp.zeros((padded,), jnp.float32)
        return {
            "contri": jnp.zeros((self._nblocks(n),), jnp.float32),
            "pending": flat,
            "step": jnp.zeros((), jnp.int32),
            "inner": self.inner.init_leaf_state(flat),
        }

    def allreduce(self, grads: Any, state: Any, axis_name: str,
                  axis_size: int) -> Tuple[Any, Any]:
        leaves, treedef = jax.tree.flatten(grads)
        n = sum(leaf.size for leaf in leaves)
        padded = self._nblocks(n) * self.block_elems
        flat = jnp.concatenate(
            [leaf.reshape(-1).astype(jnp.float32) for leaf in leaves])
        gf = jnp.zeros((padded,), jnp.float32).at[:n].set(flat)
        sendable, new_state = self._defer_schedule(gf, state)
        # the inner compressor sees ONE flat vector — its error-feedback /
        # velocity state lives on the same flat layout (init_state above)
        summed, inner_state = self.inner.allreduce_leaf(
            sendable, state["inner"], axis_name, axis_size)
        new_state["inner"] = inner_state
        out, off = [], 0
        for leaf in leaves:
            out.append(summed[off:off + leaf.size].reshape(leaf.shape)
                       .astype(leaf.dtype))
            off += leaf.size
        return treedef.unflatten(out), new_state

    def wire_bytes_leaf(self, leaf: jax.Array) -> int:
        """Amortized bytes per sync.  Non-drain steps move ~k of the
        blocks, but every ``flush_every``-th step is a drain that sends
        everything pending, so the honest steady-state average is

            (flush_every - 1) * k + 1   of   flush_every   full payloads

        (k for the top blocks each step, the full tensor on the drain)."""
        inner_bytes = self.inner.wire_bytes_leaf(leaf)
        f = self.flush_every
        frac = (min(1.0, self.k) * (f - 1) + 1.0) / f
        return int(inner_bytes * frac)
