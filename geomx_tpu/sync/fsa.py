"""FSA — Fully Synchronous Algorithm (the reference's dist_sync default).

Reference dataflow (SURVEY.md §3.3): every step, workers push gradients to
their local PS; the local tier is pure aggregation (ApplyUpdates with no
updater, kvstore_dist_server.h:502-523); local servers push the merged
gradient to the global tier, which runs the optimizer once all parties
arrive (kvstore_dist_server.h:1305-1318); fresh weights flow back down.

TPU-native: one hierarchical compressed all-reduce per step —

    g_party  = psum(g, "worker") / workers_per_party      (ICI tier)
    g_global = dc_compressor.allreduce(g_party, "dc") / P (DCN tier)

followed by an optimizer step applied identically on every device, which
keeps parameters replicated without any explicit pull.  The dc-tier
compressor slot is where Bi-Sparse / FP16 / MPQ / 2-bit plug in, exactly
the hop they compress in the reference (local server -> global server).
By default the dc compressor is wrapped in the bucketed communication
engine (compression/bucketing.py): the gradient tree fuses into a few
flat fp32 buckets, one compressed collective each, instead of one
collective per leaf (GEOMX_BUCKET_BYTES=0 opts out).  An optional
worker-tier compressor covers the reference's intra-DC fp16 mode.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
from jax import lax

from geomx_tpu.compression.base import Compressor, NoCompressor
from geomx_tpu.sync.base import SyncAlgorithm
from geomx_tpu.topology import DC_AXIS, WORKER_AXIS


class FSA(SyncAlgorithm):
    name = "fsa"
    supports_degraded = True  # renormalized survivor mean (resilience/)
    grads_replicated_after_sync = True  # hierarchical psum output
    supports_zero = True  # bucket-shard form of the same hierarchy

    def __init__(self, dc_compressor: Optional[Compressor] = None,
                 worker_compressor: Optional[Compressor] = None,
                 bucket_bytes: Optional[int] = None):
        from geomx_tpu.compression.bucketing import maybe_bucketed
        # the dc tier pays a fixed DCN round trip per collective, so the
        # default path fuses the gradient tree into a few flat buckets
        # (one compressed collective each); GEOMX_BUCKET_BYTES=0 or
        # bucket_bytes=0 restores the per-leaf path.  The ICI-tier worker
        # compressor stays per-leaf — intra-DC latency doesn't warrant
        # the re-layout.
        self.dc_compressor = maybe_bucketed(dc_compressor or NoCompressor(),
                                            bucket_bytes)
        self.worker_compressor = worker_compressor or NoCompressor()

    def _dc_init(self, params: Any) -> Any:
        """dc-tier compressor state: shard-shaped under a bound ZeRO
        plan (EF residuals live on this worker's 1/W bucket slice),
        bucket/leaf-shaped otherwise."""
        if self.zero_plan is not None:
            return self.dc_compressor.init_shard_state(params,
                                                       self.zero_plan.W)
        return self.dc_compressor.init_state(params)

    def init_state(self, params: Any, model_state: Any = None) -> Any:
        return {
            "dc_comp": self._dc_init(params),
            "worker_comp": self.worker_compressor.init_state(params),
        }

    def sync_grads(self, grads: Any, params: Any, state: Any,
                   step: jax.Array) -> Tuple[Any, Any]:
        nw = self.workers_per_party
        np_ = self.num_parties
        # intra-party tier (ICI): mean over workers
        g, wstate = self.worker_compressor.allreduce(
            grads, state["worker_comp"], WORKER_AXIS, nw)
        if nw > 1:  # single-worker parties skip the dead x/1 divide
            g = jax.tree.map(lambda x: x / nw, g)
        # degraded mode: a dead party's shard is excluded (multiplied to
        # exact zeros before the collective) and the mean renormalizes
        # over the num_live survivors — for live parties the aggregate
        # is bit-identical to the mean over survivors alone
        w = self.party_weight()
        if w is not None:
            g = jax.tree.map(lambda x: x * w, g)
        # cross-party tier (DCN): compressed mean over parties
        g, dstate = self.dc_compressor.allreduce(g, state["dc_comp"], DC_AXIS, np_)
        nl = self.num_live
        if nl > 1:
            g = jax.tree.map(lambda x: x / nl, g)
        return g, {"dc_comp": dstate, "worker_comp": wstate}

    def sync_grad_shards(self, grads: Any, params: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        """ZeRO form of :meth:`sync_grads` (train/zero.py): the same
        two-tier hierarchy on 1/W bucket shards —

            worker tier: psum_scatter(flat buckets) / W   (ICI)
            dc tier:     compressed allreduce per SHARD   (DCN)

        Each chip compresses, transfers, decompresses and (in
        train/step.py) updates only its contiguous shard of every fused
        bucket; the degraded-membership renormalization applies on the
        shards with the identical survivor-mean algebra.  Returns the
        list of global-mean bucket shards, not a gradient tree."""
        plan = self.zero_plan
        leaves = jax.tree.leaves(grads)
        bk = self.dc_compressor.zero_bucketer(leaves)
        # worker tier: the scatter IS the reduce (and a 1/W wire saving
        # per ICI link); a configured worker compressor is bypassed —
        # build_train_step warns, mirroring MultiGPS
        shards = [plan.scatter_bucket(b, WORKER_AXIS)
                  for b in bk.flatten(leaves)]
        w = self.party_weight()
        if w is not None:
            # degraded mode: identical exclusion algebra to sync_grads,
            # applied shard-wise — a dead party's shard zeroes before
            # the collective and the mean renormalizes over survivors
            shards = [x * w for x in shards]
        shards, dstate = self.dc_compressor.allreduce_shards(
            shards, state["dc_comp"], DC_AXIS, self.num_parties, bk)
        nl = self.num_live
        if nl > 1:
            shards = [x / nl for x in shards]
        return shards, dict(state, dc_comp=dstate)

    def sync_model_state(self, model_state: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        # keep non-trainable stats (BatchNorm) consistent across replicas
        if self.workers_per_party > 1:
            model_state = lax.pmean(model_state, WORKER_AXIS)
        if self.num_parties > 1:
            w = self.party_weight()
            if w is None:
                model_state = lax.pmean(model_state, DC_AXIS)
            else:
                # renormalized survivor mean, same algebra as the grads
                nl = self.num_live
                model_state = jax.tree.map(
                    lambda x: lax.psum(x * w, DC_AXIS) / nl, model_state)
        return model_state, state

    def reset_comm_state(self, params: Any, state: Any,
                         policy: str = "reset") -> Any:
        """Membership-change policy: "reset" re-initializes the dc-tier
        compressor state (error-feedback residuals accumulated against
        the old membership would replay a dead party's history into the
        renormalized mean); the worker tier is untouched — intra-party
        membership did not change."""
        state = super().reset_comm_state(params, state, policy)
        if policy == "carry":
            return state
        return dict(state, dc_comp=self._dc_init(params))

    def telemetry_scalars(self, state: Any) -> dict:
        """EF-residual magnitude of the dc-tier compressor state (the
        momentum/velocity buffers a sparse compressor holds back): the
        in-situ "how much gradient mass is parked in error feedback"
        signal (telemetry/probes.py; enabled-path only)."""
        from geomx_tpu.telemetry.probes import tree_norm
        return {"ef_residual_norm": tree_norm(state["dc_comp"])}
