"""Pipelined WAN sync: double-buffered staleness-1 dc-tier collectives.

The reference hides WAN latency with host-side machinery — P3's
priority-sliced pushes and DGT's off-critical-path channels (SURVEY.md
items 4-5) — and PR 1's bucketing cut the *number* of dc-tier
collectives, but every step still blocked on the DCN round trip before
the optimizer could run: the WAN latency sat squarely on the critical
path.  ``PipelinedSync`` takes it off entirely.

Step *t* launches the compressed dc-tier allreduce on step *t*'s
party-mean buckets, but the optimizer applies step *t-1*'s completed
aggregate, held in a double-buffer inside ``sync_state`` (the in-flight
buffer reuses the bucketed engine's flat fp32 layout,
compression/bucketing.py).  Because the collective's result is consumed
only by the *next* step, nothing in step *t*'s weight update waits on
the DCN — XLA's latency-hiding scheduler (and its collective pipeliner
on real multi-slice meshes) gets a full forward/backward of compute to
hide the WAN transfer behind.  This is the explicit double-buffering
Ok-Topk's sparse allreduce pipeline needs to reach overlap
(arXiv:2201.07598), applied at the tier EQuARX shows compressed
XLA-native collectives win at only when the scheduler can float them
(arXiv:2506.17615).

Semantics: staleness-1 data parallelism —

    w_{t+1} = w_t - lr * g_global(w_{t-1})

The first step is the pipeline's warmup bubble: it applies a zero
aggregate (the buffer starts empty) and only fills the pipeline; every
gradient is applied exactly once, one step late.  The optional
DCASGD-style compensation re-centers the stale aggregate at the weights
it is about to be applied to,

    g_comp = g + lambda * g * g * (w_t - w_{t-1})

reusing ``optim/dcasgd.py``'s correction term (reference
python/mxnet/optimizer/optimizer.py:872-925); ``w_{t-1}`` is tracked in
``sync_state`` (one extra params copy, allocated only when
``lambda > 0``).

Convergence note: a staleness-1 gradient roughly halves the stable
learning-rate headroom (the classic delayed-SGD bound) — at a stable lr
the pipelined trajectory matches the synchronous one to full accuracy
(tests/test_pipeline.py convergence parity), while an lr tuned to the
synchronous stability edge will oscillate.  That headroom is the price
paid for taking the DCN round trip off the critical path; the DCASGD
term buys some of it back.

The gradient's ICI tier (worker-axis mean) stays synchronous — intra-DC
latency is microseconds and the party-mean is the collective's input
anyway.  The model-state sync (BatchNorm stats) is double-buffered as a
whole: each step launches worker-pmean + dc-pmean of its fresh stats
into the buffer and applies the previous step's fully-aggregated stats,
so BOTH stat tiers are one step stale and NO dc-axis collective output
is consumed in-step (``bench.py --compare-pipeline`` verifies this
structurally in the DCE'd jaxpr).  ``lax.optimization_barrier`` separates the two tiers so
the flattened party-mean buckets are pinned as a unit before the DCN
launch and XLA cannot fuse the stale buffer's consumers into the
collective's dependency chain.

Composes with FSA and MixedSync by wrapping their dc-tier compressor.
HFA is rejected loudly — its global collective already fires every
K1*K2 steps off the step's critical path, and a stale milestone delta
would corrupt the milestone algebra.  MultiGPS is rejected in
``build_train_step`` (train/step.py): its ZeRO-1 update consumes the
dc-tier shard in-step by construction.

Checkpoint/restore: the in-flight buffers, the model-state buffer, and
the DCASGD previous-weights copy all live in ``sync_state``, so the
standard TrainState checkpoint round-trips the whole pipeline — a
resumed run continues the exact trajectory with no re-warmup.
"""

from __future__ import annotations

import copy
import os
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from geomx_tpu.compression.base import Compressor
from geomx_tpu.compression.bucketing import BucketedCompressor
from geomx_tpu.sync.base import SyncAlgorithm
from geomx_tpu.topology import DC_AXIS, WORKER_AXIS
from geomx_tpu.utils.profiler import get_profiler, profile_scope


def _resolve_depth(depth: Optional[int]) -> int:
    if depth is not None:
        return int(depth)
    # graftlint: disable=GXL006 — wrap-time knob
    raw = os.environ.get("GEOMX_PIPELINE_DEPTH")
    return int(float(raw)) if raw else 1


class PipelinedCompressor(Compressor):
    """Double-buffer any dc-tier compressor.

    ``allreduce`` launches the wrapped collective on this step's
    gradients, parks the result in its state, and returns the PREVIOUS
    step's completed aggregate — so the caller's downstream consumers
    (divide, optimizer) never depend on this step's collective.

    The in-flight buffer reuses the wrapped ``BucketedCompressor``'s
    flat fp32 bucket layout (one buffer per bucket, identical
    coordinates to the error-feedback state); with bucketing opted out
    it falls back to one leaf-shaped buffer per gradient leaf.
    """

    fuses_tree = True  # tree-level: never wrap in bucketing again

    def __init__(self, inner: Compressor):
        if isinstance(inner, PipelinedCompressor):
            raise ValueError("dc-tier compressor is already pipelined; "
                             "double-wrapping would add a second step of "
                             "staleness")
        self.inner = inner
        self.name = inner.name
        self._bucketed = isinstance(inner, BucketedCompressor)

    # -- state ---------------------------------------------------------------
    def init_state(self, grads: Any) -> Any:
        leaves = jax.tree.leaves(grads)
        if self._bucketed:
            bk = self.inner._bucketer(leaves)
            inflight: List[jax.Array] = [jnp.zeros((n,), jnp.float32)
                                         for n in bk.bucket_sizes]
        else:
            inflight = [jnp.zeros(jnp.shape(leaf), jnp.result_type(leaf))
                        for leaf in leaves]
        return {"inflight": inflight, "inner": self.inner.init_state(grads)}

    def init_leaf_state(self, leaf: jax.Array) -> Any:
        raise NotImplementedError(
            "PipelinedCompressor is tree-level (the in-flight buffer "
            "spans the whole gradient); per-leaf state is not supported")

    def init_shard_state(self, grads: Any, num_shards: int) -> Any:
        """ZeRO (train/zero.py): the in-flight double-buffer holds 1/W
        bucket *shards* — the aggregate parked between launch and apply
        shrinks with the worker axis exactly like the optimizer state."""
        if not self._bucketed:
            raise ValueError(
                "GEOMX_ZERO requires the bucketed dc-tier engine under "
                "the pipelined compressor (GEOMX_BUCKET_BYTES > 0)")
        leaves = jax.tree.leaves(grads)
        bk = self.inner._bucketer(leaves)
        inflight = [jnp.zeros((n // num_shards,), jnp.float32)
                    for n in bk.bucket_sizes]
        return {"inflight": inflight,
                "inner": self.inner.init_shard_state(grads, num_shards)}

    def zero_bucketer(self, leaves):
        return self.inner.zero_bucketer(leaves)

    def allreduce_shards(self, shards, state: Any, axis_name: str,
                         axis_size: int, bk) -> Tuple[List[jax.Array], Any]:
        """Double-buffered ZeRO dc tier: launch this step's per-shard
        compressed collectives, return the PREVIOUS step's completed
        shard aggregates — staleness-1 on shard-sized in-flight
        buffers."""
        prev = state["inflight"]
        # tier boundary, same contract as the replicated path: pin the
        # scattered party-mean shards as one unit before the DCN launch
        shards = list(lax.optimization_barrier(tuple(shards)))
        payload = sum(
            self.inner.inner.wire_bytes_leaf(
                jax.ShapeDtypeStruct((int(b.size),), jnp.float32))
            for b in shards)
        with profile_scope(f"{axis_name}_pipeline/launch",
                           category="comm",
                           args={"buckets": bk.num_buckets,
                                 "payload_bytes": payload}):
            launched, inner_state = self.inner.allreduce_shards(
                shards, state["inner"], axis_name, axis_size, bk)
        with profile_scope(f"{axis_name}_pipeline/apply", category="comm"):
            out = list(prev)
        return out, {"inflight": launched, "inner": inner_state}

    def peek_shards(self, state: Any) -> Tuple[List[jax.Array], Any]:
        """The completed in-flight shard aggregates plus state with the
        buffer zeroed — the ZeRO drain path."""
        prev = state["inflight"]
        zeroed = [jnp.zeros_like(b) for b in prev]
        return list(prev), dict(state, inflight=zeroed)

    # -- the double-buffered all-reduce --------------------------------------
    def allreduce(self, grads: Any, state: Any, axis_name: str,
                  axis_size: int) -> Tuple[Any, Any]:
        leaves, treedef = jax.tree.flatten(grads)
        if not leaves:
            return grads, state
        prev = state["inflight"]
        if self._bucketed:
            bk = self.inner._bucketer(leaves)
            buckets = bk.flatten(leaves)
            # tier boundary: pin the flattened ICI-tier party-mean as one
            # unit so the DCN launch below is a single scheduling island
            # XLA's latency-hiding scheduler can float — and nothing from
            # the stale-apply side fuses into its dependency chain
            buckets = list(lax.optimization_barrier(tuple(buckets)))
            with profile_scope(f"{axis_name}_pipeline/launch",
                               category="comm",
                               args={"buckets": bk.num_buckets,
                                     "payload_bytes": self.wire_bytes(grads)}):
                launched, inner_state = self.inner.allreduce_buckets(
                    buckets, state["inner"], axis_name, axis_size, bk)
            with profile_scope(f"{axis_name}_pipeline/apply",
                               category="comm"):
                out = treedef.unflatten(bk.unflatten(prev))
        else:
            pinned = treedef.unflatten(
                list(lax.optimization_barrier(tuple(leaves))))
            with profile_scope(f"{axis_name}_pipeline/launch",
                               category="comm",
                               args={"payload_bytes": self.wire_bytes(grads)}):
                launched_tree, inner_state = self.inner.allreduce(
                    pinned, state["inner"], axis_name, axis_size)
            launched = treedef.flatten_up_to(launched_tree)
            with profile_scope(f"{axis_name}_pipeline/apply",
                               category="comm"):
                out = treedef.unflatten(list(prev))
        # Chrome-trace counter: in-flight WAN bytes between launch/apply
        get_profiler().counter(f"{axis_name}_pipeline_inflight",
                               {"bytes": self.wire_bytes(grads)})
        return out, {"inflight": launched, "inner": inner_state}

    def allreduce_leaf(self, g: jax.Array, state: Any, axis_name: str,
                       axis_size: int) -> Tuple[jax.Array, Any]:
        raise NotImplementedError(
            "PipelinedCompressor is tree-level; the per-leaf path "
            "(MultiGPS) does not compose with pipelining")

    # -- draining ------------------------------------------------------------
    def peek(self, grads_like: Any, state: Any) -> Tuple[Any, Any]:
        """Return the completed in-flight aggregate as a gradient tree
        plus state with the buffer zeroed — the drain path (apply the
        last launched collective without feeding a new batch)."""
        leaves, treedef = jax.tree.flatten(grads_like)
        prev = state["inflight"]
        if self._bucketed:
            bk = self.inner._bucketer(leaves)
            out = treedef.unflatten(bk.unflatten(prev))
        else:
            out = treedef.unflatten(list(prev))
        zeroed = [jnp.zeros_like(b) for b in prev]
        return out, dict(state, inflight=zeroed)

    # -- accounting: same bytes per step as the wrapped path, one step late --
    def wire_bytes(self, grads: Any) -> int:
        return self.inner.wire_bytes(grads)

    def wire_bytes_leaf(self, leaf: jax.Array) -> int:
        return self.inner.wire_bytes_leaf(leaf)


class PipelinedSync(SyncAlgorithm):
    """Staleness-1 pipelined wrapper around FSA or MixedSync.

    Opt-in via ``GEOMX_PIPELINE_DEPTH=1`` (``get_sync_algorithm``) or by
    wrapping explicitly: ``PipelinedSync(FSA(...), dcasgd_lambda=0.04)``.
    """

    supports_degraded = True  # delegates the masked mean to FSA/MixedSync
    # the applied gradient is the previous step's completed dc aggregate
    # (plus a correction from replicated params) — replicated
    grads_replicated_after_sync = True

    def __init__(self, inner: SyncAlgorithm, depth: Optional[int] = None,
                 dcasgd_lambda: float = 0.0):
        from geomx_tpu.sync.fsa import FSA
        from geomx_tpu.sync.mixed import MixedSync
        if not isinstance(inner, (FSA, MixedSync)):
            # fail loudly (same contract as the MultiGPS check in
            # train/step.py): a user "running pipelined HFA" must not
            # silently get an unpipelined schedule or corrupt milestones
            raise ValueError(
                "GEOMX_PIPELINE_DEPTH composes with sync_mode=fsa or "
                f"mixed only, not {getattr(inner, 'name', type(inner).__name__)!r}: "
                "HFA's global tier already fires off the critical path "
                "every K1*K2 steps (a stale delta would corrupt the "
                "milestone algebra), and other algorithms have no "
                "per-step dc-tier collective to double-buffer")
        depth = _resolve_depth(depth)
        if depth != 1:
            raise ValueError(
                f"GEOMX_PIPELINE_DEPTH={depth} unsupported: only depth 1 "
                "(double buffering, staleness 1) is implemented — deeper "
                "pipelines need a ring buffer and staleness-k "
                "compensation, and hide no additional latency once the "
                "DCN round trip fits inside one step of compute")
        # shallow copy: installing the pipelined compressor must not
        # mutate the caller's algorithm — `PipelinedSync(fsa)` with `fsa`
        # also used as a synchronous baseline would silently make the
        # baseline staleness-1 too (compressor objects are stateless
        # config; their state lives in sync_state, so sharing them with
        # the original is safe)
        self.inner = copy.copy(inner)
        self.depth = depth
        self.dcasgd_lambda = float(dcasgd_lambda)
        self.name = f"pipelined_{inner.name}"
        if not isinstance(self.inner.dc_compressor, PipelinedCompressor):
            self.inner.dc_compressor = PipelinedCompressor(
                self.inner.dc_compressor)

    # -- topology ------------------------------------------------------------
    def bind_topology(self, topology) -> "PipelinedSync":
        super().bind_topology(topology)
        self.inner.bind_topology(topology)
        return self

    # -- ZeRO-sharded weight update (train/zero.py) --------------------------
    supports_zero = True

    def bind_zero(self, plan) -> "PipelinedSync":
        """Bind the ZeRO plan through to the wrapped algorithm: the
        inner FSA/MixedSync owns the shard-form sync, and the pipelined
        compressor double-buffers shard-sized in-flight aggregates.
        DCASGD staleness compensation is rejected: the correction term
        needs the previous step's weights at this worker's shard, and
        the host-side state init cannot address a per-worker slice — a
        full prev-params copy would forfeit the 1/W memory win the mode
        exists for."""
        if self.dcasgd_lambda > 0.0:
            raise ValueError(
                "GEOMX_ZERO does not compose with GEOMX_PIPELINE_DCASGD: "
                "the compensation's prev-params copy has no shard-local "
                "form; disable one of the two")
        # copy-bind, like the base contract: the caller's pipelined
        # instance may still drive a replicated run
        bound = copy.copy(self)
        bound.inner = self.inner.bind_zero(plan)
        bound.zero_plan = plan
        return bound

    def sync_grad_shards(self, grads: Any, params: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        # the wrapped algorithm runs its shard-form sync; its dc-tier
        # compressor is pipelined, so the returned shards are the
        # PREVIOUS step's completed aggregates (already tier-divided)
        shards, inner_state = self.inner.sync_grad_shards(
            grads, params, state["inner"], step)
        return shards, dict(state, inner=inner_state)

    def drain_grad_shards(self, params: Any,
                          state: Any) -> Tuple[List[jax.Array], Any]:
        """ZeRO drain: the completed in-flight shard aggregates,
        tier-divided exactly as sync_grad_shards would have, with the
        buffer zeroed.  No collectives — Trainer.drain_pipeline's
        sharded program still runs the all_gather that rebuilds
        params."""
        comp = self.inner.dc_compressor
        shards, dc_state = comp.peek_shards(state["inner"]["dc_comp"])
        nl = self.num_live
        if nl > 1:
            shards = [g / nl for g in shards]
        return shards, dict(state,
                            inner=dict(state["inner"], dc_comp=dc_state))

    # -- membership (degraded-mode WAN sync, resilience/) --------------------
    def bind_membership(self, mask) -> "PipelinedSync":
        # the inner algorithm owns the masked renormalized mean; this
        # wrapper only needs the mask for its own drain divisor
        super().bind_membership(mask)
        self.inner.bind_membership(mask)
        return self

    def reset_comm_state(self, params: Any, state: Any,
                         policy: str = "reset") -> Any:
        """Membership-change policy for the pipeline: "reset" discards
        the in-flight aggregate (it was launched under the OLD
        membership — its buckets include the dead party's shard, or lack
        the re-admitted one's) along with the inner compressor's
        residuals, costing one extra warmup bubble; "carry" keeps both
        and accepts one step whose stale aggregate mixes memberships
        (renormalized by the NEW survivor count).  The DCASGD
        previous-weights copy and the model-state buffer always carry —
        both track replicated values that survive the change."""
        s = SyncAlgorithm.reset_comm_state(self, params, state, policy)
        if policy == "carry":
            return s
        inner_state = dict(s["inner"], dc_comp=self.inner._dc_init(params))
        return dict(s, inner=inner_state)

    # -- state ---------------------------------------------------------------
    def init_state(self, params: Any, model_state: Any = None) -> Any:
        state = {"inner": self.inner.init_state(params)}
        if self.dcasgd_lambda > 0.0:
            # the weights the in-flight gradient was computed at
            state["prev_params"] = jax.tree.map(jnp.asarray, params)
        if (self.num_parties > 1 and model_state is not None
                and jax.tree.leaves(model_state)):
            # seed the model-state double-buffer with the initial stats
            # (identical on every replica), not zeros: the first applied
            # buffer must be a valid BatchNorm state
            state["inflight_ms"] = jax.tree.map(jnp.asarray, model_state)
        return state

    # -- hooks ----------------------------------------------------------------
    def forward_params(self, params: Any, state: Any) -> Any:
        return self.inner.forward_params(params, state["inner"])

    def sync_grads(self, grads: Any, params: Any, state: Any,
                   step: jax.Array) -> Tuple[Any, Any]:
        # the inner algorithm runs unmodified; its dc-tier compressor is
        # pipelined, so `g` comes back as the previous step's aggregate
        # (already tier-divided by the inner algorithm)
        g, inner_state = self.inner.sync_grads(grads, params,
                                               state["inner"], step)
        new_state = dict(state, inner=inner_state)
        if self.dcasgd_lambda > 0.0:
            lam = self.dcasgd_lambda
            g = jax.tree.map(
                lambda gg, w, wp: gg + lam * gg * gg * (w - wp),
                g, params, state["prev_params"])
            # the aggregate in flight was computed at THIS step's forward
            # weights (MixedSync: its stale pull, not the true weights)
            new_state["prev_params"] = self.inner.forward_params(
                params, inner_state)
        return g, new_state

    def sync_params(self, params: Any, state: Any,
                    step: jax.Array) -> Tuple[Any, Any]:
        params, inner_state = self.inner.sync_params(params,
                                                     state["inner"], step)
        return params, dict(state, inner=inner_state)

    def sync_model_state(self, model_state: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        if not jax.tree.leaves(model_state):
            return model_state, state
        if "inflight_ms" not in state:
            # no buffer (single party, or init_state never saw the model
            # state): keep the inner synchronous path
            ms, inner_state = self.inner.sync_model_state(
                model_state, state["inner"], step)
            return ms, dict(state, inner=inner_state)
        # both stat tiers feed the BUFFER (the applied value is the
        # previous step's fully-aggregated stats): BatchNorm aggregation
        # is one step stale as a whole, and no dc-axis result is
        # consumed in-step
        if self.workers_per_party > 1:
            model_state = lax.pmean(model_state, WORKER_AXIS)
        w = self.party_weight()
        if w is None:
            launched = lax.pmean(model_state, DC_AXIS)
        else:
            # degraded membership: the launched stat aggregate is the
            # renormalized survivor mean, same algebra as the grads
            nl = self.num_live
            launched = jax.tree.map(
                lambda x: lax.psum(x * w, DC_AXIS) / nl, model_state)
        return state["inflight_ms"], dict(state, inflight_ms=launched)

    # -- draining ------------------------------------------------------------
    def drain_grads(self, params: Any, state: Any) -> Tuple[Any, Any]:
        """The gradient tree for one drain step: the completed in-flight
        aggregate, tier-divided and compensated exactly as sync_grads
        would have, with the buffer zeroed.  No collectives — the buffer
        already holds the reduced values — so Trainer.drain_pipeline can
        run it without feeding a batch."""
        comp = self.inner.dc_compressor
        g, dc_state = comp.peek(params, state["inner"]["dc_comp"])
        nl = self.num_live  # degraded drain renormalizes over survivors
        if nl > 1:
            g = jax.tree.map(lambda x: x / nl, g)
        new_state = dict(state,
                         inner=dict(state["inner"], dc_comp=dc_state))
        if self.dcasgd_lambda > 0.0:
            lam = self.dcasgd_lambda
            g = jax.tree.map(
                lambda gg, w, wp: gg + lam * gg * gg * (w - wp),
                g, params, state["prev_params"])
        return g, new_state

    # -- telemetry (telemetry/probes.py; enabled-path only) ------------------
    def telemetry_scalars(self, state: Any) -> dict:
        """Pipeline-aware health scalars: the wrapped algorithm's EF
        residual (from the pipelined compressor's inner state, not the
        double-buffer) plus the in-flight aggregate's magnitude — a
        persistently-zero inflight norm after warmup means the pipeline
        is applying empty aggregates (exactly the silent failure a
        staleness bug produces)."""
        from geomx_tpu.telemetry.probes import tree_norm
        inner_state = state["inner"]
        dc = inner_state.get("dc_comp") if isinstance(inner_state, dict) \
            else None
        out = {}
        if isinstance(dc, dict) and "inflight" in dc:
            out["pipeline_inflight_norm"] = tree_norm(dc["inflight"])
            out["ef_residual_norm"] = tree_norm(dc.get("inner"))
        else:
            out["ef_residual_norm"] = tree_norm(dc)
        return out

    def wire_accounting(self, params: Any) -> dict:
        """The wrapped algorithm's accounting (bytes per step are
        identical — one step shifted) plus the pipeline's static shape:
        staleness and the bytes parked in flight between launch and
        apply."""
        out = self.inner.wire_accounting(params)
        out["pipeline_staleness"] = 1.0
        out["pipeline_inflight_bytes"] = out.get("dc_wire_bytes", 0.0)
        return out

    def drain_model_state(self, model_state: Any,
                          state: Any) -> Tuple[Any, Any]:
        """The model-state half of a drain step: apply the parked dc-tier
        stat aggregate (the final step's BatchNorm pmean, otherwise left
        unapplied).  The buffer keeps the applied value — identical to
        the freshly-initialized seeding, so a subsequent fit's first
        applied buffer is an identity warmup."""
        if "inflight_ms" not in state:
            return model_state, state
        parked = state["inflight_ms"]
        return parked, dict(state, inflight_ms=parked)
