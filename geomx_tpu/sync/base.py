"""SyncAlgorithm protocol.

The reference expresses synchronization imperatively: workers push/pull
against servers, servers count arrivals and gate on barriers
(kvstore_dist_server.h:1216-1370).  Here a sync algorithm is three pure
hooks around the optimizer step, executed per-device inside shard_map:

- ``forward_params``  — which parameters the worker computes gradients at
  (MixedSync workers hold *stale* copies of the global weights);
- ``sync_grads``      — gradient-space communication (FSA's hierarchical
  aggregation; identity for HFA, whose workers update locally);
- ``sync_params``     — parameter-space communication after the optimizer
  (HFA's K1/K2 averaging with milestones; stale-copy refresh for MixedSync).
"""

from __future__ import annotations

import abc
from typing import Any, Tuple

import jax


class SyncAlgorithm(abc.ABC):
    name: str = "base"

    # mesh axis sizes; set by bind_topology before tracing (they gate static
    # Python branches like axis_size == 1 short-circuits)
    num_parties: int = 1
    workers_per_party: int = 1

    def bind_topology(self, topology) -> "SyncAlgorithm":
        self.num_parties = topology.num_parties
        self.workers_per_party = topology.workers_per_party
        return self

    def init_state(self, params: Any, model_state: Any = None) -> Any:
        """Algorithm state from example (unsharded, single-replica) params.

        ``model_state`` (non-trainable collections, e.g. BatchNorm stats)
        is offered so algorithms that double-buffer the model-state sync
        (PipelinedSync) can size/seed their buffer; most algorithms
        ignore it."""
        return {}

    def forward_params(self, params: Any, state: Any) -> Any:
        return params

    def sync_grads(self, grads: Any, params: Any, state: Any,
                   step: jax.Array) -> Tuple[Any, Any]:
        return grads, state

    def sync_params(self, params: Any, state: Any,
                    step: jax.Array) -> Tuple[Any, Any]:
        return params, state

    def sync_model_state(self, model_state: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        """Hook for non-trainable model state (e.g. BatchNorm statistics).

        Threads the sync-algorithm state like the other hooks so stateful
        model-state schedules (PipelinedSync's double-buffered dc-tier
        pmean) are expressible; stateless algorithms return ``state``
        unchanged."""
        return model_state, state
