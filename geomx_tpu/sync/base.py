"""SyncAlgorithm protocol.

The reference expresses synchronization imperatively: workers push/pull
against servers, servers count arrivals and gate on barriers
(kvstore_dist_server.h:1216-1370).  Here a sync algorithm is three pure
hooks around the optimizer step, executed per-device inside shard_map:

- ``forward_params``  — which parameters the worker computes gradients at
  (MixedSync workers hold *stale* copies of the global weights);
- ``sync_grads``      — gradient-space communication (FSA's hierarchical
  aggregation; identity for HFA, whose workers update locally);
- ``sync_params``     — parameter-space communication after the optimizer
  (HFA's K1/K2 averaging with milestones; stale-copy refresh for MixedSync).

Degraded-mode membership (resilience/): algorithms that set
``supports_degraded`` accept a static live-party mask via
``bind_membership`` — the dc-tier aggregate becomes a renormalized mean
over surviving parties, and the mask is part of the traced step
(changing it is a recompile boundary).
"""

from __future__ import annotations

import abc
from typing import Any, Optional, Tuple

import jax
import numpy as np


def _private_dc_copy(dc_compressor):
    """Shallow-copy a dc-tier compressor stack so ``bind_zero``'s
    re-padding (pad_to, cached bucket layouts) lands on a private
    instance: the caller's compressor may still back a replicated
    baseline whose layout must not shift under it."""
    import copy

    from geomx_tpu.compression.bucketing import BucketedCompressor
    from geomx_tpu.sync.pipeline import PipelinedCompressor
    dc = copy.copy(dc_compressor)
    bucketed = dc
    if isinstance(dc, PipelinedCompressor):
        dc.inner = copy.copy(dc.inner)
        bucketed = dc.inner
    if isinstance(bucketed, BucketedCompressor):
        bucketed._bucketers = {}              # never share the layout cache
    return dc


class SyncAlgorithm(abc.ABC):
    name: str = "base"

    # mesh axis sizes; set by bind_topology before tracing (they gate static
    # Python branches like axis_size == 1 short-circuits)
    num_parties: int = 1
    workers_per_party: int = 1

    # telemetry (telemetry/probes.py): True when sync_grads returns a
    # gradient REPLICATED across the mesh (hierarchical aggregation:
    # FSA/MixedSync/PipelinedSync).  Algorithms keeping per-device
    # gradients (HFA's identity sync_grads — workers update locally)
    # leave it False, so the replicated-value probes (grad norm,
    # aggregate density) are skipped instead of silently publishing one
    # shard's local value under a replicated out-spec.
    grads_replicated_after_sync: bool = False

    # degraded-mode membership (resilience/): None = every party live.
    # Set only via bind_membership; algorithms opt in with
    # supports_degraded (the mask changes the dc-tier algebra, and an
    # algorithm that ignored it would silently average in a dead party's
    # stale shard).
    live_parties: Optional[Tuple[bool, ...]] = None
    supports_degraded: bool = False

    # ZeRO-sharded weight update (train/zero.py, GEOMX_ZERO): algorithms
    # that can express their gradient sync on 1/W bucket shards —
    # psum_scatter worker tier, per-shard compressed dc tier — opt in
    # with supports_zero and implement sync_grad_shards.  None = the
    # replicated update path.  Contract: shard-shaped dc-tier state
    # MUST live under the "dc_comp" key of sync_state — the host-side
    # layout handlers (host_zero_state/place_zero_state/
    # reshard_zero_state) route shard-vs-replicated on that key.
    zero_plan = None
    supports_zero: bool = False

    def bind_topology(self, topology) -> "SyncAlgorithm":
        self.num_parties = topology.num_parties
        self.workers_per_party = topology.workers_per_party
        return self

    # ---- membership (degraded-mode WAN sync) -------------------------------

    def bind_membership(self, mask) -> "SyncAlgorithm":
        """Bind a live-party mask (a MembershipEpoch or any boolean
        sequence).  Call after bind_topology; an all-live mask clears
        degraded mode.  The mask is STATIC in the traced step — a dead
        party's shard is excluded by multiplication to exact zeros
        before the dc collective and the mean renormalizes over
        survivors — so changing it is a recompile boundary
        (``Trainer.apply_membership``)."""
        from geomx_tpu.topology import normalize_live_mask
        mask = normalize_live_mask(getattr(mask, "live_mask", mask),
                                   self.num_parties)
        if all(mask):
            self.live_parties = None
            return self
        if not self.supports_degraded:
            raise ValueError(
                f"sync algorithm {self.name!r} does not support a "
                "degraded membership mask: its aggregation algebra has "
                "no renormalized-survivor form (FSA, MixedSync and "
                "PipelinedSync do)")
        self.live_parties = mask
        return self

    @property
    def num_live(self) -> int:
        """Parties contributing to the dc tier under the bound mask."""
        if self.live_parties is None:
            return self.num_parties
        return sum(self.live_parties)

    def party_weight(self):
        """This party's 0/1 contribution weight under the bound mask, or
        None when every party is live (no masking work to trace).  Valid
        only inside shard_map (reads the dc axis index)."""
        if self.live_parties is None:
            return None
        import jax.numpy as jnp
        from jax import lax
        from geomx_tpu.topology import DC_AXIS
        m = jnp.asarray(np.asarray(self.live_parties, np.float32))
        return m[lax.axis_index(DC_AXIS)]

    # ---- ZeRO-sharded weight update (train/zero.py) ------------------------

    def bind_zero(self, plan) -> "SyncAlgorithm":
        """Return a copy of this algorithm bound to a
        :class:`~geomx_tpu.train.zero.ZeroPlan` (GEOMX_ZERO): the
        gradient sync switches to the bucket-shard form and the dc-tier
        state becomes shard-shaped.  NEVER mutates ``self`` — binding
        re-pads the dc compressor's bucket layout, and a handed-in
        algorithm may also serve as a replicated baseline (the same
        contract as ``PipelinedSync``'s shallow copy).  Algorithms whose
        aggregation has no shard form (HFA's milestone algebra lives in
        parameter space) reject loudly."""
        if not self.supports_zero:
            raise ValueError(
                f"sync algorithm {self.name!r} does not support the "
                "ZeRO-sharded weight update (GEOMX_ZERO): its "
                "aggregation has no bucket-shard form (FSA, MixedSync "
                "and PipelinedSync do)")
        import copy
        bound = copy.copy(self)
        bound.dc_compressor = _private_dc_copy(self.dc_compressor)
        plan.bind_compressor(bound.dc_compressor)
        bound.zero_plan = plan
        return bound

    def sync_grad_shards(self, grads: Any, params: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        """ZeRO gradient sync: return (list of global-mean flat bucket
        *shards* — this worker's 1/W slice of every fused bucket — and
        the new sync state).  Only called when a zero plan is bound."""
        raise NotImplementedError(
            f"{self.name!r} bound a zero plan but implements no "
            "sync_grad_shards")

    def reset_comm_state(self, params: Any, state: Any,
                         policy: str = "reset") -> Any:
        """Apply the membership-change residual policy to (host-side,
        unreplicated) sync state: ``"reset"`` re-initializes dc-tier
        communication state (error-feedback residuals, pipeline
        double-buffers), ``"carry"`` keeps it (docs/resilience.md
        documents the trade-off).  Base: nothing to reset."""
        if policy not in ("reset", "carry"):
            raise ValueError(f"unknown residual policy {policy!r}: "
                             "expected 'reset' or 'carry'")
        return state

    def init_state(self, params: Any, model_state: Any = None) -> Any:
        """Algorithm state from example (unsharded, single-replica) params.

        ``model_state`` (non-trainable collections, e.g. BatchNorm stats)
        is offered so algorithms that double-buffer the model-state sync
        (PipelinedSync) can size/seed their buffer; most algorithms
        ignore it."""
        return {}

    def forward_params(self, params: Any, state: Any) -> Any:
        return params

    def sync_grads(self, grads: Any, params: Any, state: Any,
                   step: jax.Array) -> Tuple[Any, Any]:
        return grads, state

    def sync_params(self, params: Any, state: Any,
                    step: jax.Array) -> Tuple[Any, Any]:
        return params, state

    def sync_model_state(self, model_state: Any, state: Any,
                         step: jax.Array) -> Tuple[Any, Any]:
        """Hook for non-trainable model state (e.g. BatchNorm statistics).

        Threads the sync-algorithm state like the other hooks so stateful
        model-state schedules (PipelinedSync's double-buffered dc-tier
        pmean) are expressible; stateless algorithms return ``state``
        unchanged."""
        return model_state, state

    # ---- telemetry (telemetry/probes.py) -----------------------------------

    def telemetry_scalars(self, state: Any) -> dict:
        """In-graph health scalars from this algorithm's sync state
        (party-LOCAL values; the probe layer folds them to the party
        mean).  Called inside the traced step ONLY when telemetry is
        enabled, so implementations are free to add reductions — the
        disabled path never sees them.  Base: nothing to report."""
        return {}

    def wire_accounting(self, params: Any) -> dict:
        """Static per-step wire-volume accounting (plain Python floats,
        resolved at trace/build time): what each tier puts on the wire
        per step, and the achieved compression ratio vs the dense fp32
        payload.  Algorithms with a dc-tier compressor get the generic
        accounting for free."""
        out = {}
        dc = getattr(self, "dc_compressor", None)
        if dc is not None:
            leaves = jax.tree.leaves(params)
            dense = float(sum(
                leaf.size * np.dtype(leaf.dtype).itemsize for leaf in leaves))
            if self.zero_plan is not None:
                # ZeRO (train/zero.py): per-chip dc payload is the
                # compressed 1/W bucket shard; the worker tier's
                # scatter/gather bytes ride along so telemetry sees the
                # full decomposition
                out.update(self.zero_plan.wire_accounting(params))
                wire = out.get("dc_wire_bytes", 0.0)
                # the per-party dense baseline shrinks with the shard too
                dense = dense / self.zero_plan.W
            else:
                wire = float(dc.wire_bytes(params))
            out["dc_wire_bytes"] = wire
            out["dc_dense_bytes"] = dense
            out["dc_compression_ratio"] = dense / wire if wire else 1.0
        wc = getattr(self, "worker_compressor", None)
        if wc is not None and self.zero_plan is None:
            out["worker_wire_bytes"] = float(wc.wire_bytes(params))
        return out
