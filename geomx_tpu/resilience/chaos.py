"""Deterministic fault injection: seeded chaos schedules.

The reference injects faults with one global knob — ``PS_DROP_MSG``
drops N% of received data messages (van.cc:510-512), which our host
plane mirrors in ``service/protocol.should_drop``.  That is a *rate*,
not a *scenario*: it cannot express "party 1 goes dark at step 3 for 4
steps, then a 30% loss epoch at step 10", and an unseeded rate is not
reproducible.  This module turns failures into data:

- :class:`ChaosSchedule` — a seeded, sorted list of
  :class:`ChaosEvent`\\ s, built from a compact spec string
  (``GEOMX_CHAOS_SCHEDULE``), from code, or sampled reproducibly with
  :meth:`ChaosSchedule.random`;
- :class:`ChaosEngine` — replays the schedule in-process against a
  :class:`~geomx_tpu.resilience.liveness.PartyLivenessController`
  (party blackouts / link flaps -> membership epochs) and against the
  existing ``should_drop`` hook (drop-rate epochs override
  ``GEOMX_DROP_MSG`` for a window of steps).

Spec format (semicolon-separated events; see docs/resilience.md):

    seed=<n>                       optional, reseeds the shared drop RNG
    blackout@<step>:party=<p>[,steps=<n>]   party dies (auto-readmit
                                            after n steps when given)
    flap@<step>:party=<p>[,steps=<n>]       short blackout, default 1 step
    readmit@<step>:party=<p>                explicit re-admission
    drop@<step>:rate=<pct>[,steps=<n>]      message-drop epoch (host
                                            transports; cleared after n)
    throttle@<step>:party=<p>,factor=<f>[,steps=<n>]
                                            link-quality shaping: party
                                            p's WAN uplink throughput is
                                            multiplied by f (0 < f <= 1;
                                            0.125 = 8x slower), cleared
                                            after n steps when given
    delay@<step>:party=<p>,ms=<m>[,steps=<n>]
                                            link-quality shaping: m ms
                                            of added latency per WAN
                                            round on party p's link
    kill@<step>:node=server|scheduler|shard<i>[,restart_after=<n>]
                                            host-plane process death:
                                            drives the installed node
                                            lifecycle hook; with
                                            restart_after, the paired
                                            restart@ fires n steps
                                            later.  ``shard<i>``
                                            targets ONE shard of the
                                            key-range sharded global
                                            tier — the rest of the
                                            tier keeps merging
    restart@<step>:node=server|scheduler|shard<i>    explicit restart
    corrupt@<step>:party=<p>,rate=<r>[,steps=<n>]
                                            bit-corruption epoch: r% of
                                            party p's retry-protected
                                            data frames have one bit
                                            flipped at send time (the
                                            wire-CRC gate detects, the
                                            retry path re-delivers);
                                            party=-1 matches every
                                            sender

Example: ``"seed=7;blackout@3:party=1,steps=4;drop@10:rate=30,steps=5"``.

``throttle``/``delay`` ride the same in-process transport hook pattern
``drop`` uses (``protocol.set_link_shaping_override`` next to
``set_drop_rate_override``): the server's relay hop sleeps the shaped
extra time inside its ``RelayToGlobal`` span, so WAN *degradation* —
not just blackout/loss — is deterministically replayable, and the
LinkObservatory measures exactly what the schedule injected (the
controller acceptance harness of ``bench.py --compare-control``).

Determinism contract: the same spec (or the same ``random`` arguments)
produces the same event sequence, and the engine reseeds the protocol
drop RNG from the schedule seed, so a chaos run is replayable bit for
bit — the property every resilience test and
``bench.py --compare-resilience`` stands on.
"""

from __future__ import annotations

import dataclasses
import random as _random
import re
from typing import Iterable, List, Optional, Tuple

# event kinds after duration expansion (a blackout/flap/drop/throttle/
# delay WITH a ``steps=`` window expands into its paired restore event
# at build time, so the engine itself is a stateless replayer)
_KINDS = ("blackout", "readmit", "drop_rate", "drop_clear",
          "throttle", "throttle_clear", "delay", "delay_clear",
          "kill", "restart", "corrupt", "corrupt_clear")

# kill/restart targets: the host plane's central singletons, plus
# "shard<i>" for one shard of the key-range sharded global tier
_NODES = ("server", "scheduler")

_SHARD_NODE = re.compile(r"^shard(\d+)$")


def _valid_node(node: str) -> bool:
    return node in _NODES or bool(_SHARD_NODE.match(node))


def shard_node_index(node: str) -> "Optional[int]":
    """``"shard3" -> 3``; None for the non-shard targets."""
    m = _SHARD_NODE.match(node)
    return int(m.group(1)) if m else None

# host-plane lifecycle hook (``kill@``/``restart@``): the in-process
# counterpart of protocol.set_drop_rate_override — whoever owns the
# processes (the recovery bench, a test harness, a supervisor) installs
# a callable ``hook(action, node)`` with action in ("kill", "restart")
# and node in _NODES, and the engine drives it on schedule.
_lifecycle_hook = None


def set_node_lifecycle_hook(hook) -> None:
    """Install (or clear, with None) the process-lifecycle hook the
    ``kill@``/``restart@`` chaos verbs drive."""
    global _lifecycle_hook
    _lifecycle_hook = hook


@dataclasses.dataclass(frozen=True, order=True)
class ChaosEvent:
    step: int
    kind: str          # one of _KINDS
    party: int = -1    # blackout/readmit/throttle/delay/corrupt
    rate: int = 0      # drop_rate / corrupt, percent 0-100
    factor: float = 0.0  # throttle: throughput multiplier (0 < f <= 1)
    ms: int = 0        # delay: added latency per WAN round
    node: str = ""     # kill/restart: "server" | "scheduler"

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown chaos event kind {self.kind!r}; "
                             f"valid: {_KINDS}")
        if self.step < 0:
            raise ValueError(f"chaos event step must be >= 0 ({self.step})")
        if self.kind in ("kill", "restart") and not _valid_node(self.node):
            raise ValueError(
                f"chaos {self.kind} targets node= one of {_NODES} or "
                f"shard<i> (got {self.node!r})")


class ChaosSchedule:
    """An immutable, step-sorted sequence of chaos events plus the seed
    that makes drop-rate epochs reproducible."""

    def __init__(self, events: Iterable[ChaosEvent], seed: int = 0):
        self.events: Tuple[ChaosEvent, ...] = tuple(sorted(events))
        self.seed = int(seed)

    def events_at(self, step: int) -> List[ChaosEvent]:
        return [e for e in self.events if e.step == step]

    @property
    def last_step(self) -> int:
        return max((e.step for e in self.events), default=-1)

    def spec(self) -> str:
        """Canonical spec string (round-trips through ``from_spec``) —
        what the bench record and test failures print."""
        parts = [f"seed={self.seed}"]
        for e in self.events:
            if e.kind in ("blackout", "readmit"):
                parts.append(f"{e.kind}@{e.step}:party={e.party}")
            elif e.kind == "drop_rate":
                parts.append(f"drop@{e.step}:rate={e.rate}")
            elif e.kind == "drop_clear":
                parts.append(f"dropclear@{e.step}")
            elif e.kind == "throttle":
                parts.append(
                    f"throttle@{e.step}:party={e.party},factor={e.factor:g}")
            elif e.kind == "throttle_clear":
                parts.append(f"throttleclear@{e.step}:party={e.party}")
            elif e.kind == "delay":
                parts.append(f"delay@{e.step}:party={e.party},ms={e.ms}")
            elif e.kind == "delay_clear":
                parts.append(f"delayclear@{e.step}:party={e.party}")
            elif e.kind in ("kill", "restart"):
                parts.append(f"{e.kind}@{e.step}:node={e.node}")
            elif e.kind == "corrupt":
                parts.append(
                    f"corrupt@{e.step}:party={e.party},rate={e.rate}")
            else:  # corrupt_clear
                parts.append(f"corruptclear@{e.step}:party={e.party}")
        return ";".join(parts)

    # ---- constructors ------------------------------------------------------

    @classmethod
    def from_config(cls, cfg) -> "Optional[ChaosSchedule]":
        """The ``GEOMX_CHAOS_SCHEDULE`` consumption point: parse the
        config's schedule spec, or None when no chaos is configured."""
        spec = getattr(cfg, "chaos_schedule", "") or ""
        return cls.from_spec(spec) if spec.strip() else None

    @classmethod
    def from_spec(cls, spec: str) -> "ChaosSchedule":
        """Parse the ``GEOMX_CHAOS_SCHEDULE`` format (module docstring)."""
        events: List[ChaosEvent] = []
        seed = 0
        for raw in filter(None, (s.strip() for s in spec.split(";"))):
            if raw.startswith("seed="):
                seed = int(raw.split("=", 1)[1])
                continue
            if "@" not in raw:
                raise ValueError(f"bad chaos event {raw!r}: expected "
                                 "kind@step[:key=val,...]")
            head, _, tail = raw.partition(":")
            kind, step_s = head.split("@", 1)
            step = int(step_s)
            kv = {}
            for item in filter(None, (t.strip() for t in tail.split(","))):
                k, _, v = item.partition("=")
                if not _:
                    raise ValueError(f"bad chaos option {item!r} in {raw!r}")
                # every option is an integer except the throttle factor
                # (a throughput multiplier in (0, 1]) and the kill/
                # restart target node (a role name)
                if k == "node":
                    kv[k] = v
                else:
                    kv[k] = float(v) if k == "factor" else int(v)
            known = {"blackout": {"party", "steps"},
                     "flap": {"party", "steps"},
                     "readmit": {"party"},
                     "drop": {"rate", "steps"},
                     "dropclear": set(),
                     "throttle": {"party", "factor", "steps"},
                     "throttleclear": {"party"},
                     "delay": {"party", "ms", "steps"},
                     "delayclear": {"party"},
                     "kill": {"node", "restart_after"},
                     "restart": {"node"},
                     "corrupt": {"party", "rate", "steps"},
                     "corruptclear": {"party"}}
            if kind not in known:
                raise ValueError(f"unknown chaos kind {kind!r}; valid: "
                                 f"{sorted(known)}")
            if set(kv) - known[kind]:
                raise ValueError(f"chaos {kind!r} does not take "
                                 f"{sorted(set(kv) - known[kind])}")
            if kind in ("blackout", "flap"):
                party = kv["party"]
                events.append(ChaosEvent(step, "blackout", party=party))
                # a flap is a short blackout; both auto-readmit when a
                # window is given (flap defaults to one step)
                steps = kv.get("steps", 1 if kind == "flap" else 0)
                if steps:
                    events.append(ChaosEvent(step + steps, "readmit",
                                             party=party))
            elif kind == "readmit":
                events.append(ChaosEvent(step, "readmit", party=kv["party"]))
            elif kind == "drop":
                rate = kv["rate"]
                if not 0 <= rate <= 100:
                    raise ValueError(f"drop rate {rate} not in [0, 100]")
                events.append(ChaosEvent(step, "drop_rate", rate=rate))
                if kv.get("steps"):
                    events.append(ChaosEvent(step + kv["steps"],
                                             "drop_clear"))
            elif kind == "throttle":
                factor = kv["factor"]
                if not 0.0 < factor <= 1.0:
                    raise ValueError(
                        f"throttle factor {factor} not in (0, 1]")
                events.append(ChaosEvent(step, "throttle",
                                         party=kv["party"], factor=factor))
                if kv.get("steps"):
                    events.append(ChaosEvent(int(step + kv["steps"]),
                                             "throttle_clear",
                                             party=kv["party"]))
            elif kind == "throttleclear":
                events.append(ChaosEvent(step, "throttle_clear",
                                         party=kv["party"]))
            elif kind == "delay":
                ms = kv["ms"]
                if ms < 0:
                    raise ValueError(f"delay ms {ms} must be >= 0")
                events.append(ChaosEvent(step, "delay",
                                         party=kv["party"], ms=ms))
                if kv.get("steps"):
                    events.append(ChaosEvent(int(step + kv["steps"]),
                                             "delay_clear",
                                             party=kv["party"]))
            elif kind == "delayclear":
                events.append(ChaosEvent(step, "delay_clear",
                                         party=kv["party"]))
            elif kind in ("kill", "restart"):
                events.append(ChaosEvent(step, kind,
                                         node=str(kv["node"])))
                # kill@S:node=X,restart_after=N expands into its paired
                # restart, like every other duration-bearing verb
                if kind == "kill" and kv.get("restart_after"):
                    events.append(ChaosEvent(
                        int(step + kv["restart_after"]), "restart",
                        node=str(kv["node"])))
            elif kind == "corrupt":
                rate = kv["rate"]
                if not 0 <= rate <= 100:
                    raise ValueError(
                        f"corrupt rate {rate} not in [0, 100]")
                events.append(ChaosEvent(step, "corrupt",
                                         party=kv.get("party", -1),
                                         rate=rate))
                if kv.get("steps"):
                    events.append(ChaosEvent(int(step + kv["steps"]),
                                             "corrupt_clear",
                                             party=kv.get("party", -1)))
            elif kind == "corruptclear":
                events.append(ChaosEvent(step, "corrupt_clear",
                                         party=kv.get("party", -1)))
            else:  # dropclear
                events.append(ChaosEvent(step, "drop_clear"))
        return cls(events, seed=seed)

    @classmethod
    def random(cls, seed: int, steps: int, num_parties: int,
               blackouts: int = 1, blackout_len: Tuple[int, int] = (2, 5),
               drop_epochs: int = 0,
               drop_rate: Tuple[int, int] = (10, 50),
               keep_party: int = 0,
               node_kills: int = 0,
               nodes: Tuple[str, ...] = ("server",),
               kill_restart_after: Tuple[int, int] = (1, 3),
               corrupt_epochs: int = 0,
               corrupt_rate: Tuple[int, int] = (20, 40),
               throttle_epochs: int = 0,
               throttle_factor: Tuple[float, float] = (0.1, 0.5)
               ) -> "ChaosSchedule":
        """Sample a reproducible schedule: ``blackouts`` party outages
        (never ``keep_party`` — someone must survive) and ``drop_epochs``
        loss windows, all from ``random.Random(seed)`` so the same
        arguments always produce the same scenario.

        Multi-node scenarios (the 16+ party chaos fleet): ``node_kills``
        kill+restart pairs sampled over ``nodes`` (e.g.
        ``("shard0", "shard1", "scheduler")`` — each kill picks a node,
        a start step, and a restart ``kill_restart_after`` steps later;
        at most one outstanding kill per node at a time, and a pair
        whose restart would land past the run is dropped
        (``node_kills`` is an upper bound), so a schedule never
        restarts a node that is not down and never leaves one
        permanently dead.  ``corrupt_epochs`` /
        ``throttle_epochs`` sample seeded bit-flip and link-shaping
        windows over non-kept parties."""
        if num_parties < 2 and blackouts:
            raise ValueError("party blackouts need num_parties >= 2")
        for n in nodes:
            if not _valid_node(n):
                raise ValueError(
                    f"random: node {n!r} is not one of {_NODES} or "
                    "shard<i>")
        rng = _random.Random(seed)
        events: List[ChaosEvent] = []
        candidates = [p for p in range(num_parties) if p != keep_party]
        for _ in range(blackouts):
            party = rng.choice(candidates)
            length = rng.randint(*blackout_len)
            start = rng.randint(1, max(1, steps - length - 1))
            events.append(ChaosEvent(start, "blackout", party=party))
            events.append(ChaosEvent(start + length, "readmit", party=party))
        for _ in range(drop_epochs):
            start = rng.randint(1, max(1, steps - 2))
            length = rng.randint(1, max(1, steps - start - 1))
            events.append(ChaosEvent(start, "drop_rate",
                                     rate=rng.randint(*drop_rate)))
            events.append(ChaosEvent(start + length, "drop_clear"))
        down_until: dict = {}   # node -> step its restart fires
        for _ in range(node_kills):
            node = rng.choice(list(nodes))
            gap = rng.randint(*kill_restart_after)
            start = rng.randint(1, max(1, steps - gap - 1))
            if start <= down_until.get(node, 0):
                # this node is still down at the sampled step: push the
                # kill past its pending restart (never a double-kill)
                start = down_until[node] + 1
            if start + gap >= steps:
                # the pair no longer fits the run: a kill whose restart
                # cannot fire would leave the node permanently dead and
                # make the schedule unsatisfiable — drop it (node_kills
                # is an upper bound)
                continue
            events.append(ChaosEvent(start, "kill", node=node))
            events.append(ChaosEvent(start + gap, "restart", node=node))
            down_until[node] = start + gap
        for _ in range(corrupt_epochs):
            start = rng.randint(1, max(1, steps - 2))
            length = rng.randint(1, max(1, steps - start - 1))
            party = rng.choice(candidates) if candidates else -1
            events.append(ChaosEvent(start, "corrupt", party=party,
                                     rate=rng.randint(*corrupt_rate)))
            events.append(ChaosEvent(start + length, "corrupt_clear",
                                     party=party))
        for _ in range(throttle_epochs):
            start = rng.randint(1, max(1, steps - 2))
            length = rng.randint(1, max(1, steps - start - 1))
            party = rng.choice(candidates) if candidates else -1
            factor = round(rng.uniform(*throttle_factor), 3)
            events.append(ChaosEvent(start, "throttle", party=party,
                                     factor=factor))
            events.append(ChaosEvent(start + length, "throttle_clear",
                                     party=party))
        return cls(events, seed=seed)


class ChaosEngine:
    """Replays a schedule against the liveness controller and the
    ``should_drop`` hook.  Call :meth:`tick` once per training step
    (before running the step); it returns the events applied so the
    caller can react (rebind membership, log, assert)."""

    def __init__(self, schedule: ChaosSchedule,
                 controller: Optional[object] = None,
                 drive_drop_hook: bool = True):
        self.schedule = schedule
        self.controller = controller
        self.drive_drop_hook = drive_drop_hook
        self._applied_through = -1
        if drive_drop_hook:
            # reproducibility: the message-loss AND bit-corruption
            # patterns inside their epochs derive from the schedule
            # seed, not process history
            from geomx_tpu.service.protocol import (reseed_corrupt_rng,
                                                    reseed_drop_rng)
            reseed_drop_rng(schedule.seed)
            reseed_corrupt_rng(schedule.seed)

    def tick(self, step: int) -> List[ChaosEvent]:
        """Apply every event scheduled in ``(last_tick, step]`` (skipped
        steps still fire — a caller that advances by epochs must not
        silently lose a mid-epoch blackout)."""
        if step <= self._applied_through:
            return []
        fired = [e for e in self.schedule.events
                 if self._applied_through < e.step <= step]
        self._applied_through = step
        for e in fired:
            self._apply(e)
        return fired

    def _apply(self, e: ChaosEvent) -> None:
        if e.kind in ("blackout", "readmit"):
            if self.controller is None:
                raise ValueError(
                    f"chaos event {e} needs a PartyLivenessController "
                    "(construct ChaosEngine(schedule, controller))")
            if e.kind == "blackout":
                self.controller.mark_dead(e.party)
            else:
                self.controller.mark_live(e.party)
        elif e.kind in ("kill", "restart"):
            # host-plane process lifecycle: driven through the installed
            # hook, never directly — the engine knows WHEN, the owner of
            # the processes knows HOW (crash semantics, durable dirs,
            # ports).  bench.py --compare-recovery is the reference user.
            if _lifecycle_hook is None:
                raise ValueError(
                    f"chaos event {e} needs a node lifecycle hook "
                    "(set_node_lifecycle_hook)")
            _lifecycle_hook(e.kind, e.node)
        elif not self.drive_drop_hook:
            return
        elif e.kind in ("drop_rate", "drop_clear"):
            from geomx_tpu.service.protocol import set_drop_rate_override
            set_drop_rate_override(e.rate if e.kind == "drop_rate" else None)
        elif e.kind in ("corrupt", "corrupt_clear"):
            from geomx_tpu.service.protocol import set_corruption_override
            set_corruption_override(
                e.party, e.rate if e.kind == "corrupt" else None)
        else:
            # link-quality shaping: same in-process hook pattern as the
            # drop override — the transports consult it, the engine
            # installs/clears it on schedule
            from geomx_tpu.service.protocol import set_link_shaping_override
            if e.kind == "throttle":
                set_link_shaping_override(e.party, factor=e.factor)
            elif e.kind == "throttle_clear":
                set_link_shaping_override(e.party, factor=None)
            elif e.kind == "delay":
                set_link_shaping_override(e.party, delay_ms=e.ms)
            else:  # delay_clear
                set_link_shaping_override(e.party, delay_ms=None)

    def close(self) -> None:
        """Clear any installed drop/shaping override (idempotent) — pair
        with construction in tests so one chaos run cannot leak loss or
        link degradation into the next."""
        if self.drive_drop_hook:
            from geomx_tpu.service.protocol import (
                clear_corruption_overrides, clear_link_shaping_overrides,
                set_drop_rate_override)
            set_drop_rate_override(None)
            clear_link_shaping_overrides()
            clear_corruption_overrides()

    def __enter__(self) -> "ChaosEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
