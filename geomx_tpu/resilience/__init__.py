"""Elastic resilience: party-liveness control, degraded-mode WAN sync,
and deterministic fault injection.

The reference *detects* failures (heartbeats -> scheduler dead list,
van.cc:1147-1160; re-admission via ``is_recovery``, van.cc:165-212) but a
dead party still stalls every synchronous round.  This subsystem closes
the loop:

- ``liveness``  — ``PartyLivenessController`` turns heartbeat / roster
  signals into a versioned **membership epoch** (live-party mask +
  renormalization weight) that the sync algorithms and the Trainer
  consume;
- degraded-mode sync lives in ``sync/`` (FSA / MixedSync / PipelinedSync
  accept the mask via ``bind_membership``; the dc-tier aggregate becomes
  a renormalized mean over surviving parties);
- ``chaos``     — seeded, reproducible schedules of party blackouts,
  link flaps and message-drop epochs that drive the controller
  in-process (tests, ``bench.py --compare-resilience``).

See docs/resilience.md for the membership/catch-up protocol and the
chaos schedule format.
"""

from geomx_tpu.resilience.chaos import ChaosEngine, ChaosEvent, ChaosSchedule
from geomx_tpu.resilience.liveness import (MembershipEpoch,
                                           PartyLivenessController)

__all__ = ["MembershipEpoch", "PartyLivenessController", "ChaosSchedule",
           "ChaosEvent", "ChaosEngine"]
