"""Party-liveness control: heartbeats/roster signals -> membership epochs.

The reference's liveness machinery stops at detection: the scheduler
keeps a dead list (Postoffice::GetDeadNodes, postoffice.h:187) and
re-admits restarted nodes with ``is_recovery`` (van.cc:165-212), but
nothing *acts* on a dead party — a synchronous round waits forever.
``PartyLivenessController`` closes that gap for the SPMD plane: it folds
per-node liveness (``utils.heartbeat.HeartbeatMonitor``, or the
scheduler's cluster-wide dead list) into a per-*party* verdict and
publishes it as a versioned :class:`MembershipEpoch` — the live-party
mask plus its renormalization weight.  The Trainer binds an epoch via
``apply_membership`` (the recompile boundary: membership is a static
property of the sharded step, the design "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training" argues for replica
sets in general), and the sync algorithms renormalize the dc-tier mean
over survivors.

Re-admission catch-up: a returning party must receive the authoritative
state (params + optimizer + sync residuals/buffers) *before* it rejoins
the collective — :func:`pack_catchup` / :func:`unpack_catchup` serialize
exactly the trees ``utils/checkpoint.py`` checkpoints, so catch-up and
restore share one format by construction.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass(frozen=True)
class MembershipEpoch:
    """A versioned snapshot of which parties participate in the dc tier.

    ``version`` increases on every mask change (monotone, never reused),
    so consumers can order epochs and detect staleness; ``live_mask[p]``
    is True when party ``p`` contributes to the dc-tier aggregate."""

    version: int
    live_mask: Tuple[bool, ...]

    @property
    def num_parties(self) -> int:
        return len(self.live_mask)

    @property
    def num_live(self) -> int:
        return sum(self.live_mask)

    @property
    def all_live(self) -> bool:
        return all(self.live_mask)

    @property
    def renorm_weight(self) -> float:
        """The survivor-mean divisor's reciprocal: the dc-tier aggregate
        under this epoch is ``psum(g * mask) * renorm_weight``."""
        return 1.0 / self.num_live

    def live_parties(self) -> List[int]:
        return [p for p, ok in enumerate(self.live_mask) if ok]


class PartyLivenessController:
    """Publishes membership epochs from node-level liveness signals.

    A party maps to one or more node ids (its local server, its data
    feeder, ...) via :meth:`bind_party`; the party is declared dead when
    ANY of its bound nodes is dead — a party missing any member cannot
    complete its intra-party round.  Chaos / operator intervention uses
    :meth:`mark_dead` / :meth:`mark_live` directly (no node binding
    needed), which is how the deterministic fault-injection harness
    drives the controller in-process.

    ``min_live`` guards the floor: a transition that would leave fewer
    live parties raises instead of publishing an epoch the run cannot
    execute (an all-dead mesh has no survivor mean to renormalize to).
    """

    def __init__(self, num_parties: int,
                 monitor: Optional[Any] = None,
                 min_live: int = 1,
                 timeout_s: Optional[float] = None):
        if num_parties < 1:
            raise ValueError("num_parties must be >= 1")
        if not 1 <= min_live <= num_parties:
            raise ValueError(f"min_live must be in [1, {num_parties}]")
        self.num_parties = int(num_parties)
        self.monitor = monitor          # utils.heartbeat.HeartbeatMonitor
        self.min_live = int(min_live)
        self.timeout_s = timeout_s
        self._party_nodes: Dict[int, Set[int]] = {}
        self._forced_dead: Set[int] = set()
        self._mask: Tuple[bool, ...] = (True,) * num_parties
        self._version = 0
        self._subs: List[Callable[[MembershipEpoch], None]] = []
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, cfg, monitor: Optional[Any] = None
                    ) -> "PartyLivenessController":
        """Build from a GeoConfig: ``num_parties``, the
        ``GEOMX_RESILIENCE_MIN_LIVE`` floor, and the heartbeat timeout
        (``GEOMX_HEARTBEAT_TIMEOUT``) all come from the config."""
        return cls(num_parties=cfg.num_parties, monitor=monitor,
                   min_live=max(1, min(cfg.num_parties,
                                       int(getattr(cfg,
                                                   "resilience_min_live",
                                                   1)))),
                   timeout_s=getattr(cfg, "heartbeat_timeout_s", None))

    # ---- wiring ------------------------------------------------------------

    def bind_party(self, party: int, node_id: int) -> None:
        """Attach a heartbeat identity to a party (repeatable: a party
        may carry several nodes)."""
        self._check_party(party)
        with self._lock:
            self._party_nodes.setdefault(party, set()).add(int(node_id))
        if self.monitor is not None:
            self.monitor.register(int(node_id))

    def subscribe(self, cb: Callable[[MembershipEpoch], None]) -> None:
        """Call ``cb(epoch)`` on every epoch change (from the thread that
        triggered the transition)."""
        self._subs.append(cb)

    # ---- the published epoch ----------------------------------------------

    @property
    def epoch(self) -> MembershipEpoch:
        with self._lock:
            return MembershipEpoch(self._version, self._mask)

    # ---- transitions -------------------------------------------------------

    def mark_dead(self, party: int) -> MembershipEpoch:
        """Force a party dead (chaos blackout / operator eviction)."""
        self._check_party(party)
        with self._lock:
            self._forced_dead.add(party)
            epoch, changed = self._recompute_locked(
                self._monitor_dead_locked())
        return self._publish(epoch, changed)

    def mark_live(self, party: int) -> MembershipEpoch:
        """Clear a forced-dead mark (chaos re-admission).  The party
        rejoins the mask only if its bound nodes are also beating."""
        self._check_party(party)
        with self._lock:
            self._forced_dead.discard(party)
            epoch, changed = self._recompute_locked(
                self._monitor_dead_locked())
        return self._publish(epoch, changed)

    def poll(self, dead_nodes: Optional[Sequence[int]] = None,
             timeout_s: Optional[float] = None) -> MembershipEpoch:
        """Re-evaluate the mask from node liveness and publish.

        ``dead_nodes``: an externally-observed dead list (e.g. the
        scheduler's ``SchedulerClient.dead_nodes()`` — the roster-epoch
        consumer path); default consults the bound HeartbeatMonitor."""
        with self._lock:
            if dead_nodes is None:
                dead = self._monitor_dead_locked(timeout_s)
            else:
                dead = set(int(n) for n in dead_nodes)
            epoch, changed = self._recompute_locked(dead)
        return self._publish(epoch, changed)

    # ---- internals ---------------------------------------------------------

    def _check_party(self, party: int) -> None:
        if not 0 <= party < self.num_parties:
            raise ValueError(f"party {party} out of range "
                             f"[0, {self.num_parties})")

    def _monitor_dead_locked(self,
                             timeout_s: Optional[float] = None) -> Set[int]:
        if self.monitor is None:
            return set()
        return set(self.monitor.dead_nodes(
            timeout_s if timeout_s is not None else self.timeout_s))

    def _recompute_locked(self, dead_nodes: Set[int]):
        mask = tuple(
            p not in self._forced_dead
            and not (self._party_nodes.get(p, set()) & dead_nodes)
            for p in range(self.num_parties))
        if sum(mask) < self.min_live:
            raise RuntimeError(
                f"membership floor violated: {sum(mask)} live parties < "
                f"min_live={self.min_live} (mask {mask}) — the run cannot "
                "degrade further; restore a party or abort")
        changed = mask != self._mask
        if changed:
            self._mask = mask
            self._version += 1
        return MembershipEpoch(self._version, self._mask), changed

    def _publish(self, epoch: MembershipEpoch,
                 changed: bool) -> MembershipEpoch:
        # subscribers run OUTSIDE the lock: a callback is free to read
        # .epoch or trigger further transitions without deadlocking
        if changed:
            try:
                self._record_epoch(epoch)
            except Exception:
                # telemetry must never abort the membership publish: an
                # unwritable event log (full disk mid-failure) or a bad
                # GEOMX_TELEMETRY_EVENTS_MAX_BYTES would otherwise skip
                # every subscriber and leave degraded sync unconfigured
                pass
            for cb in list(self._subs):
                cb(epoch)
        return epoch

    def _record_epoch(self, epoch: MembershipEpoch) -> None:
        """Membership telemetry (docs/telemetry.md): the epoch version
        and live-party gauges answer "is the mesh degraded RIGHT NOW and
        since which transition" without scraping logs, and the event log
        keeps the transition history with masks."""
        from geomx_tpu.telemetry import get_registry, log_event
        reg = get_registry()
        reg.gauge("geomx_membership_version",
                  "Version of the current membership epoch"
                  ).set(epoch.version)
        reg.gauge("geomx_live_parties",
                  "Parties contributing to the dc-tier aggregate"
                  ).set(epoch.num_live)
        per_party = reg.gauge("geomx_party_live",
                              "Per-party liveness (1 = live)", ("party",))
        for p, ok in enumerate(epoch.live_mask):
            per_party.labels(party=str(p)).set(1.0 if ok else 0.0)
        reg.counter("geomx_membership_transitions_total",
                    "Published membership epoch changes").inc()
        log_event("membership_epoch", version=epoch.version,
                  live_mask=list(epoch.live_mask),
                  num_live=epoch.num_live)


# ---- re-admission catch-up ------------------------------------------------

def pack_catchup(state: Any) -> bytes:
    """Serialize the authoritative state a re-admitted party receives
    before it rejoins the collective.  Delegates to the checkpoint tree
    format (utils/checkpoint.py) so catch-up and restore round-trip the
    SAME trees — params, optimizer state, model state, AND sync state
    (compressor residuals / pipeline buffers), which is what keeps the
    error-feedback trajectory consistent across a membership change."""
    from geomx_tpu.utils.checkpoint import tree_to_bytes
    return tree_to_bytes(state)


def unpack_catchup(blob: bytes, target: Any = None) -> Any:
    """Inverse of :func:`pack_catchup`; with ``target`` the leaves are
    re-placed with the target's shardings (same contract as
    ``load_checkpoint``)."""
    from geomx_tpu.utils.checkpoint import tree_from_bytes
    return tree_from_bytes(blob, target=target)
