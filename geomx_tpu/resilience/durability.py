"""Durable host-plane state: atomic snapshots + an append-only journal.

PR 3 made the *parties* survivable; the host plane's central processes —
``GeoPSServer`` (the parameter store, merge rounds, per-sender round
counts) and ``GeoScheduler`` (roster, id table, epoch) — still held
everything in memory, so one process death lost the whole training run
(ROADMAP item 4 names "failover" as a prerequisite for any serving
claim).  :class:`DurableStateStore` is the shared persistence primitive
both sides of the host plane stand on:

- a **snapshot** file written atomically with the same temp-file +
  ``os.replace`` pattern ``utils/checkpoint.save_checkpoint`` and the
  profiler dumps use — a crash mid-write never corrupts the previous
  snapshot;
- an **append-only journal** of incremental records (one per completed
  merge round / roster mutation), each length-prefixed and CRC32-framed
  so a crash mid-append leaves a *detectably* torn tail that replay
  truncates instead of mis-parsing;
- a persisted **generation counter** bumped once per process start —
  the restart token every server/scheduler reply carries so clients
  *detect* a restart and run the session-resume handshake
  (docs/resilience.md "Host-plane recovery").

Recovery contract: ``load()`` returns the last snapshot plus every
journal record appended after it (in order); the owner replays the
records over the snapshot to reach its exact pre-crash durable state.
Records carry a monotone sequence number; ``compact()`` folds the
journal into a fresh snapshot and truncates, and replay skips records
the snapshot already covers — so a crash at any point of the compaction
never double-applies or loses a record.

Values are host objects (numpy arrays, primitives).  Device trees go
through ``utils/checkpoint.tree_to_bytes`` *at the owner* (the server
serializes optimizer-state trees that way), keeping this module free of
jax imports — the scheduler process deliberately never imports jax.
"""

from __future__ import annotations

import io
import os
import pickle
import struct
import threading
import zlib
from typing import Any, List, Optional, Tuple

_REC_HEAD = struct.Struct("<II")   # payload length, crc32(payload)
_SNAP_MAGIC = b"GXSNAP1\n"
_JOURNAL_MAGIC = b"GXJRNL1\n"


class DurabilityError(RuntimeError):
    """A durable file exists but cannot be read as written (wrong magic,
    corrupt snapshot body).  A *torn journal tail* is NOT an error — it
    is the expected shape of a crash mid-append and is truncated."""


def _atomic_write(path: str, data: bytes) -> None:
    # the shared atomic-replace owner (utils/atomicio.py) with
    # fsync=True: data fsynced before the rename, the DIRECTORY fsynced
    # after it — compact() truncates the journal right after the
    # snapshot replace, and without the directory fsync a power loss
    # could persist the truncation but not the rename, losing every
    # record since the previous snapshot
    from geomx_tpu.utils.atomicio import atomic_write_bytes
    atomic_write_bytes(path, data, fsync=True)


class DurableStateStore:
    """One named durable state: ``<dir>/<name>.snap`` + ``.journal`` +
    ``.gen``.  Thread-safe; every mutation is crash-safe in the sense
    above.  ``name`` must be unique per logical node within the
    directory (the server uses its rank, the scheduler ``scheduler``).
    """

    def __init__(self, directory: str, name: str,
                 fsync_journal: bool = True):
        self.directory = str(directory)
        self.name = str(name)
        os.makedirs(self.directory, exist_ok=True)
        # a SIGKILL between mkstemp and the rename leaves a uniquely
        # named orphan temp; the restart (this constructor) is the one
        # place that can reclaim it without racing a live writer
        from geomx_tpu.utils.atomicio import sweep_stale_tmp
        sweep_stale_tmp(self.directory)
        self._snap_path = os.path.join(self.directory, name + ".snap")
        self._journal_path = os.path.join(self.directory, name + ".journal")
        self._gen_path = os.path.join(self.directory, name + ".gen")
        self._lock = threading.Lock()
        self._fsync = bool(fsync_journal)
        self._journal_f = None
        self._seq = 0            # last sequence number written
        self._snap_seq = 0       # sequence the snapshot covers through
        self.records_appended = 0

    # ---- generation token --------------------------------------------------

    def bump_generation(self) -> int:
        """Read-increment-persist the generation counter (atomic via the
        snapshot write pattern).  Call once per process start; the
        result is the restart token replies carry."""
        with self._lock:
            gen = self._read_generation_locked() + 1
            _atomic_write(self._gen_path, str(gen).encode("ascii"))
            return gen

    def generation(self) -> int:
        with self._lock:
            return self._read_generation_locked()

    def _read_generation_locked(self) -> int:
        try:
            with open(self._gen_path, "rb") as f:
                return int(f.read().strip() or 0)
        except FileNotFoundError:
            return 0
        except ValueError as e:
            raise DurabilityError(
                f"unreadable generation file {self._gen_path}: {e}") from e

    # ---- snapshot ----------------------------------------------------------

    def snapshot(self, state: Any) -> None:
        """Atomically persist ``state`` as the new snapshot.  Does NOT
        touch the journal — use :meth:`compact` to fold and truncate."""
        with self._lock:
            self._snapshot_locked(state)

    def _snapshot_locked(self, state: Any) -> None:
        payload = pickle.dumps({"seq": self._seq, "state": state},
                               protocol=4)
        _atomic_write(self._snap_path,
                      _SNAP_MAGIC + _REC_HEAD.pack(
                          len(payload), zlib.crc32(payload)) + payload)
        self._snap_seq = self._seq

    def compact(self, state: Any) -> None:
        """Snapshot ``state`` then truncate the journal.  Crash-safe in
        both orders: snapshot-then-crash leaves old journal records with
        seq <= the snapshot's, which replay skips; a crash before the
        snapshot leaves everything as it was."""
        with self._lock:
            self._snapshot_locked(state)
            if self._journal_f is not None:
                try:
                    self._journal_f.close()
                except OSError:
                    pass
                self._journal_f = None
            _atomic_write(self._journal_path, _JOURNAL_MAGIC)

    # ---- journal -----------------------------------------------------------

    def append(self, record: Any) -> int:
        """Append one journal record; returns its sequence number.  The
        frame is ``[len][crc32][pickle]`` so a torn tail (crash mid-
        write) is detected and truncated on replay, never mis-parsed."""
        with self._lock:
            self._seq += 1
            payload = pickle.dumps({"seq": self._seq, "rec": record},
                                   protocol=4)
            f = self._journal_handle_locked()
            f.write(_REC_HEAD.pack(len(payload), zlib.crc32(payload)))
            f.write(payload)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
            self.records_appended += 1
            return self._seq

    def _journal_handle_locked(self):
        if self._journal_f is None:
            fresh = not os.path.exists(self._journal_path)
            self._journal_f = open(self._journal_path, "ab")
            if fresh or os.path.getsize(self._journal_path) == 0:
                self._journal_f.write(_JOURNAL_MAGIC)
                self._journal_f.flush()
        return self._journal_f

    # ---- recovery ----------------------------------------------------------

    def load(self) -> Tuple[Optional[Any], List[Any]]:
        """``(snapshot_state | None, [records after the snapshot])``.
        Replaying the records over the snapshot reconstructs the exact
        pre-crash durable state.  Also primes the internal sequence
        counter so appends after a restart continue the numbering, and
        PHYSICALLY truncates a torn tail — otherwise post-restart
        appends would land *behind* the torn bytes and a second crash
        would silently lose every record since the first restart."""
        with self._lock:
            snap_state, snap_seq = self._load_snapshot_locked()
            records, last_seq, valid_end = \
                self._load_journal_locked(snap_seq)
            self._seq = max(snap_seq, last_seq)
            self._snap_seq = snap_seq
            if valid_end is not None:
                if self._journal_f is not None:
                    try:
                        self._journal_f.close()
                    except OSError:
                        pass
                    self._journal_f = None
                with open(self._journal_path, "r+b") as f:
                    f.truncate(valid_end)
            return snap_state, records

    def _load_snapshot_locked(self) -> Tuple[Optional[Any], int]:
        try:
            with open(self._snap_path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None, 0
        if not blob.startswith(_SNAP_MAGIC):
            raise DurabilityError(
                f"{self._snap_path}: bad snapshot magic")
        body = blob[len(_SNAP_MAGIC):]
        if len(body) < _REC_HEAD.size:
            raise DurabilityError(f"{self._snap_path}: truncated header")
        n, crc = _REC_HEAD.unpack_from(body, 0)
        payload = body[_REC_HEAD.size:_REC_HEAD.size + n]
        # the snapshot was written atomically, so corruption here is
        # disk damage, not a crash artifact — refuse to guess
        if len(payload) != n or zlib.crc32(payload) != crc:
            raise DurabilityError(
                f"{self._snap_path}: snapshot payload fails its CRC")
        doc = pickle.loads(payload)
        return doc["state"], int(doc["seq"])

    def _load_journal_locked(self, min_seq: int
                             ) -> Tuple[List[Any], int, Optional[int]]:
        """Returns ``(records, last_seq, torn_truncate_at)`` where the
        third element is the byte offset of the last VALID record's end
        when torn bytes follow it (None for a clean file)."""
        try:
            with open(self._journal_path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return [], min_seq, None
        if not blob:
            return [], min_seq, None  # crashed between create and magic
        if not blob.startswith(_JOURNAL_MAGIC):
            raise DurabilityError(
                f"{self._journal_path}: bad journal magic")
        buf = io.BytesIO(blob[len(_JOURNAL_MAGIC):])
        records: List[Any] = []
        last_seq = min_seq
        valid_end = len(_JOURNAL_MAGIC)
        while True:
            head = buf.read(_REC_HEAD.size)
            if len(head) < _REC_HEAD.size:
                break  # clean EOF or torn length header: stop
            n, crc = _REC_HEAD.unpack(head)
            payload = buf.read(n)
            if len(payload) != n or zlib.crc32(payload) != crc:
                break  # torn tail (crash mid-append): truncate here
            valid_end = len(_JOURNAL_MAGIC) + buf.tell()
            doc = pickle.loads(payload)
            seq = int(doc["seq"])
            if seq <= min_seq:
                continue  # the snapshot already covers this record
            records.append(doc["rec"])
            last_seq = max(last_seq, seq)
        torn = valid_end if valid_end < len(blob) else None
        return records, last_seq, torn

    # ---- introspection / teardown ------------------------------------------

    def journal_bytes(self) -> int:
        try:
            return os.path.getsize(self._journal_path)
        except OSError:
            return 0

    def snapshot_bytes(self) -> int:
        try:
            return os.path.getsize(self._snap_path)
        except OSError:
            return 0

    def close(self) -> None:
        with self._lock:
            if self._journal_f is not None:
                try:
                    self._journal_f.close()
                except OSError:
                    pass
                self._journal_f = None


def durable_dir_from_env(explicit: Optional[str] = None) -> Optional[str]:
    """The one resolution point for ``GEOMX_DURABLE_DIR``: an explicit
    argument wins, the env var is the deployment default, and None/""
    means the node runs memory-only (pre-PR-10 behavior)."""
    if explicit is not None:
        return explicit or None
    # graftlint: disable=GXL006 — host-plane knob
    return os.environ.get("GEOMX_DURABLE_DIR") or None
